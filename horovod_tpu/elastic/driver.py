"""Elastic driver: discovery loop, slot assignment, worker lifecycle.

Reference: horovod/runner/elastic/driver.py:69 ElasticDriver — background
discovery thread (1 s period) runs the user script; on host changes it
notifies workers; ``start()`` waits for min slots, assigns ranks
*preserving existing slots* (driver.py:240-272), spawns a worker per new
slot; worker exits are recorded by WorkerStateRegistry which triggers
``resume()`` (host blacklist + rank reassignment + respawn).  The reset
limit counts world reshapes, not individual worker exits, so one multi-slot
host failure is one reset.

TPU build notification channel: instead of per-worker socket RPC services
(elastic/worker.py:46), the driver publishes a monotonically increasing
``discovery/update`` sequence (+ the host set) in the rendezvous KV store;
each worker polls it from a daemon thread (WorkerNotificationManager in
__init__.py) and surfaces HostsUpdatedInterrupt at the next
``state.commit()`` — same contract, one fewer service.  World records carry
a ``version``; workers re-rendezvousing after a reset wait for a version
newer than the world they left (elastic/__init__.py
_refresh_world_from_rendezvous), which closes the stale-record race.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import get_logger
from ..runner import hosts as _hosts
from ..runner import safe_shell_exec
from ..runner.http_server import RendezvousServer
from .. import config as _config
from .discovery import HostDiscovery, HostDiscoveryScript, HostManager
from .registration import WorkerStateRegistry

DISCOVER_INTERVAL_S = 1.0
# How long a scaled-out worker gets to exit on its own before SIGTERM.
DECOMMISSION_GRACE_S = float(os.environ.get(
    "HVD_TPU_DECOMMISSION_GRACE_S", "30"))


class Worker:
    def __init__(self, host: str, slot: int, version: int = 0):
        self.host = host
        self.slot = slot
        self.version = version  # refreshed on every world reactivation
        self.thread: Optional[threading.Thread] = None
        self.terminate_event = threading.Event()
        # Set (under the driver lock) when a launch-scoped worker body
        # confirmed no newer world adopted it and is about to return —
        # adoption must replace, not keep, a retired record.
        self.retired = False
        # Graceful decommission (scale-down): the slot fell out of the new
        # world, so the exit is not a failure and must not blacklist the
        # (still healthy) host.
        self.decommissioned = False
        self.decommission_timer: Optional[threading.Timer] = None


class ElasticDriver:
    """driver.py:69 ElasticDriver analog."""

    def __init__(self, rendezvous: RendezvousServer,
                 discovery: HostDiscovery,
                 min_np: int, max_np: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 cooldown_range: Optional[Tuple[float, float]] = None,
                 timeout: float = 600.0,
                 verbose: bool = False):
        self.rendezvous = rendezvous
        # Preemption awareness (SURVEY §5.3 TPU equivalent): worker-host
        # sentinels publish maintenance notices into the rendezvous KV
        # scope "preempt"; wrapping the discovery filters those hosts out
        # of the discoverable world so the reshape happens BEFORE the VM
        # dies, and _terminate_workers_on_lost_hosts drains their workers
        # gracefully instead of terminating them.
        from .preemption import PREEMPT_SCOPE, PreemptionAwareDiscovery

        def _marked_hosts():
            return set(rendezvous.scan_scope(PREEMPT_SCOPE).keys())

        self._preempt_marked = _marked_hosts
        discovery = PreemptionAwareDiscovery(discovery, _marked_hosts)
        self.host_manager = HostManager(discovery, cooldown_range)
        self.host_manager.min_required = min_np  # starvation-escape floor
        self.min_np = min_np
        self.max_np = max_np or min_np
        self.timeout = timeout
        self.registry = WorkerStateRegistry(self, self.host_manager,
                                            reset_limit=reset_limit)
        self._workers: Dict[Tuple[str, int], Worker] = {}
        self._assignments: List[_hosts.SlotInfo] = []
        self._world_version = 0
        self._update_seq = 0  # discovery-update sequence, own counter
        self._shutdown = threading.Event()
        self._error_message: Optional[str] = None
        self._resumes_inflight = 0
        self._resume_pending = False
        self._resume_rerun = False
        self._lock = threading.RLock()
        self._worker_cmd_fn: Optional[Callable] = None
        self._discovery_thread = threading.Thread(
            target=self._discover_loop, daemon=True, name="hvd-elastic-disc")

    # -- lifecycle -----------------------------------------------------------

    def start(self, create_worker_fn: Callable) -> None:
        """Wait for min slots and launch the initial world (driver.py:102).
        World size is min(max_np, available slots)."""
        self._worker_cmd_fn = create_worker_fn
        self.wait_for_available_slots(self.min_np)
        self._activate_world()
        self._discovery_thread.start()

    def wait_for_available_slots(self, min_np: int) -> None:
        deadline = time.time() + self.timeout
        while not self._shutdown.is_set():
            self.host_manager.update_available_hosts()
            if self.host_manager.available_slots >= min_np:
                return
            if time.time() > deadline:
                raise RuntimeError(
                    f"Timed out waiting for {min_np} slots "
                    f"(--start-timeout / HOROVOD_ELASTIC_TIMEOUT); "
                    f"currently available: "
                    f"{self.host_manager.available_slots}")
            time.sleep(DISCOVER_INTERVAL_S)

    def stop(self, error_message: Optional[str] = None) -> None:
        self._error_message = error_message
        self._shutdown.set()
        with self._lock:
            for w in self._workers.values():
                w.terminate_event.set()
        # Deterministic discovery-loop teardown: the loop re-checks
        # _shutdown within one DISCOVER_INTERVAL_S; join it so stop()
        # leaves no poller behind (daemon stays the backstop for a wedged
        # discovery script).  _resume calls stop() from its own thread,
        # never from the discovery thread itself, but guard anyway.
        t = self._discovery_thread
        if t.is_alive() and t is not threading.current_thread():
            t.join(timeout=DISCOVER_INTERVAL_S + 5)

    def join(self) -> None:
        """Wait until the job settles: no live workers and no resume pending
        or in flight (or the driver was stopped).  Worker threads register
        failures *before* deregistering themselves (registration ordering in
        _launch_worker), so there is no idle gap where a pending resume is
        invisible."""
        while not self._shutdown.is_set():
            with self._lock:
                idle = (not self._workers and self._resumes_inflight == 0
                        and not self._resume_pending)
            if idle:
                return
            time.sleep(0.05)

    @property
    def error_message(self) -> Optional[str]:
        return self._error_message

    @property
    def world_version(self) -> int:
        return self._world_version

    @property
    def resume_in_flight(self) -> bool:
        """True while a world reshape is pending or being applied (used by
        the registry to classify worker deaths as reshape casualties)."""
        with self._lock:
            return self._resume_pending or self._resumes_inflight > 0

    def retire_if_settled(self, hostname: str, local_rank: int,
                          world_version: int, terminate_event=None):
        """Launch-scoped worker bodies (the Spark task-pool protocol runs
        ONE launch per world) call this before returning after a clean
        launch.  ATOMICALLY with the adoption decision (_activate_world
        runs under the same lock): if a newer world has adopted this
        (host, local_rank), returns ``(False, new_slot, new_version)`` —
        the caller must serve the new world; otherwise marks the worker
        record retired (adoption will replace it, never keep it) and
        returns ``(True, None, version)`` — safe to exit.  Without this
        handshake a thread checking the version lock-free could decide to
        exit just as adoption kept its still-alive record, leaving the
        slot silently unserved.

        ``terminate_event`` identifies the CALLER's worker record (each
        record owns a unique event): a thread whose record was already
        replaced — or marked for termination — must settle, not serve,
        or it would double-launch a slot its replacement already owns."""
        with self._lock:
            w = self._workers.get((hostname, local_rank))
            mine_record = w is not None and (
                terminate_event is None or
                w.terminate_event is terminate_event)
            if self._world_version != world_version and mine_record and \
                    not w.terminate_event.is_set():
                mine = [s for s in self._assignments
                        if (s.hostname, s.local_rank) ==
                        (hostname, local_rank)]
                if mine:
                    return False, mine[0], self._world_version
            if mine_record:
                w.retired = True
            return True, None, self._world_version

    def current_assignments(self) -> List[_hosts.SlotInfo]:
        with self._lock:
            return list(self._assignments)

    # -- discovery loop ------------------------------------------------------

    def _discover_loop(self):
        while not self._shutdown.is_set():
            try:
                res = self.host_manager.update_available_hosts()
            except Exception as e:  # discovery script hiccup: keep going
                get_logger().warning("discovery failed: %s", e)
                res = 0
            if res == 1:
                # Hosts removed: terminate their workers and reshape the
                # world so survivors re-rendezvous into fresh records.
                self._notify_workers_host_changes(res)
                self._terminate_workers_on_lost_hosts()
                self.request_resume(additive=False, count_reset=True)
            elif res == 2:
                if self.host_manager.available_slots > \
                        len(self._assignments) and \
                        len(self._assignments) < self.max_np:
                    # Pure scale-up: workers will interrupt & re-rendezvous
                    # at next commit; prepare the new world eagerly.
                    self._notify_workers_host_changes(res)
                    self.request_resume(additive=True, count_reset=False)
                # else: an additive discovery result the driver will NOT
                # act on — e.g. a blacklisted host re-appearing after its
                # cooldown while the world is already at capacity.  Do NOT
                # notify: the interrupt would send every worker into a
                # re-rendezvous for a world version that is never coming
                # (this exact wedge deadlocked the crash-recovery e2e
                # whenever the blacklist cooldown re-added the host).
            self._shutdown.wait(DISCOVER_INTERVAL_S)

    def _terminate_workers_on_lost_hosts(self):
        marked = self._preempt_marked()
        with self._lock:
            current = set(self.host_manager.current_hosts.keys())
            for (host, slot), w in self._workers.items():
                if host not in current:
                    if host in marked:
                        # Preempt-marked host: still ALIVE, dying soon.
                        # Give its worker a drain window — the discovery
                        # notification (published just before this call)
                        # raises HostsUpdatedInterrupt at the worker's
                        # next commit, so state lands on disk/peers before
                        # the reshape; terminate is only the grace-period
                        # fallback.  decommissioned=True keeps the exit
                        # from being recorded as a failure (no blacklist:
                        # the marker itself keeps the host out).
                        if not w.decommissioned:
                            w.decommissioned = True
                            w.decommission_timer = threading.Timer(
                                DECOMMISSION_GRACE_S, w.terminate_event.set)
                            w.decommission_timer.start()
                    else:
                        w.terminate_event.set()

    def _notify_workers_host_changes(self, update_res: int):
        """KV-store sequence bump — worker poll threads pick it up
        (WorkerNotificationClient analog, driver.py:210-238)."""
        with self._lock:
            self._update_seq += 1
            seq = self._update_seq
        self.rendezvous.put(
            "discovery", "update",
            json.dumps({"version": seq,
                        "res": update_res,
                        "hosts": self.host_manager.current_hosts}).encode())

    # -- world (re)activation ------------------------------------------------

    def _activate_world(self):
        """Compute assignments preserving existing slots (driver.py:240-272)
        and publish them; spawn workers for slots that lack one."""
        with self._lock:
            np_ = min(self.max_np, self.host_manager.available_slots)
            new_assignments = self._assign_preserving(np_)
            self._assignments = new_assignments
            self._world_version += 1
            self.registry.reset(len(new_assignments))
            for slot in new_assignments:
                payload = json.dumps(
                    {**slot.to_dict(), "version": self._world_version})
                self.rendezvous.put(
                    "rendezvous", f"slot/{slot.hostname}/{slot.local_rank}",
                    payload.encode())
                self.rendezvous.put("rendezvous", f"rank/{slot.rank}",
                                    payload.encode())
            self.rendezvous.put("rendezvous", "size",
                                str(len(new_assignments)).encode())
            self.rendezvous.put(
                "rendezvous", "world",
                json.dumps({"version": self._world_version,
                            "size": len(new_assignments)}).encode())
            new_keys = {(s.hostname, s.local_rank)
                        for s in new_assignments}
            for key, w in list(self._workers.items()):
                if key not in new_keys and not w.decommissioned:
                    # Slot-granular scale-DOWN: the host survived but lost
                    # slots (e.g. localhost:3 -> localhost:2).  The worker
                    # is NOT killed here: an abrupt death while peers'
                    # jax.distributed clients are live FATALs the
                    # survivors (TF coordination service error polling).
                    # Instead it discovers during re-rendezvous that no
                    # slot record carries the new world version and exits
                    # 0 on its own (elastic/__init__.py
                    # _refresh_world_from_rendezvous); SIGTERM is only the
                    # grace-period fallback.  No failure record, no
                    # blacklist (elastic_common.py:305 shrink semantics).
                    w.decommissioned = True
                    w.decommission_timer = threading.Timer(
                        DECOMMISSION_GRACE_S, w.terminate_event.set)
                    w.decommission_timer.start()
            for slot in new_assignments:
                key = (slot.hostname, slot.local_rank)
                w = self._workers.get(key)
                if w is not None and (
                        w.retired or
                        w.thread is None or not w.thread.is_alive() or
                        (w.decommissioned and w.terminate_event.is_set())):
                    # A worker whose thread already finished cannot serve
                    # the new world — launch-scoped worker bodies (the
                    # Spark task-pool protocol runs ONE launch per world)
                    # return when their launch completes, so adopting the
                    # record would leave the slot silently unserved.  Same
                    # for a decommissioned worker past the point of no
                    # return.  Replace with a fresh launch; the old
                    # thread's deregister pops only its own registration,
                    # so the overwrite is safe.
                    w = None
                if w is not None:
                    # Surviving worker adopted into the new world: clear
                    # any in-flight decommission (a shrink-then-grow flap
                    # must not SIGTERM a now-valid worker) and make later
                    # failures fresh events, not stale ones.
                    if w.decommission_timer is not None:
                        w.decommission_timer.cancel()
                        w.decommission_timer = None
                    w.decommissioned = False
                    w.version = self._world_version
                else:
                    self._launch_worker(slot)

    def _assign_preserving(self, np_: int) -> List[_hosts.SlotInfo]:
        """Rank assignment preferring hosts that already run workers so
        surviving processes keep their (host, local_rank) slot
        (driver.py:240-272)."""
        hosts_now = self.host_manager.current_hosts
        existing_hosts = [h for h, _ in self._workers.keys()]
        ordered = sorted(
            hosts_now.keys(),
            key=lambda h: (0 if h in existing_hosts else 1, h))
        host_list = [_hosts.HostInfo(h, hosts_now[h]) for h in ordered]
        return _hosts.get_host_assignments(host_list, min(
            np_, sum(hosts_now.values())))

    def _launch_worker(self, slot: _hosts.SlotInfo):
        worker = Worker(slot.hostname, slot.local_rank, self._world_version)
        self._workers[(slot.hostname, slot.local_rank)] = worker
        spawn_version = self._world_version

        def run():
            ret = self._worker_cmd_fn(slot, worker.terminate_event,
                                      spawn_version)
            key = (slot.hostname, slot.local_rank)

            def deregister():
                with self._lock:
                    # Pop only OUR registration: the slot may have been
                    # re-launched (scale down then up) while this thread
                    # was still reaping the old process.
                    if self._workers.get(key) is worker:
                        self._workers.pop(key, None)

            if self._shutdown.is_set() or worker.decommissioned:
                # Shutdown or graceful scale-down: the nonzero exit of a
                # terminated process is not a training failure.
                deregister()
                return
            # Record BEFORE deregistering so join() never sees an idle gap
            # between worker exit and the resume request.
            if ret == 0:
                self.registry.record_success(slot.hostname, slot.local_rank,
                                             worker.version)
            else:
                self.registry.record_failure(slot.hostname, slot.local_rank,
                                             worker.version)
            deregister()

        worker.thread = threading.Thread(target=run, daemon=True,
                                         name=f"hvd-worker-{slot.rank}")
        worker.thread.start()

    # -- resume --------------------------------------------------------------

    def request_resume(self, additive: bool = False,
                       count_reset: bool = True) -> bool:
        """Schedule one world reshape; concurrent requests coalesce.
        Returns True when a new resume was scheduled (used by the registry
        to count resets per reshape, not per failed worker).

        A request that lands while a resume is already running is NOT
        dropped: it marks the running resume for a re-run.  Every
        notification promises the workers a world-version bump (their
        refresh blocks on one); silently absorbing a second host change
        into an in-flight reshape left them waiting for a version that
        never came (two discovery updates 12 s apart under load wedged the
        scale-down e2e this way)."""
        if self._shutdown.is_set():
            return False
        with self._lock:
            if self._resume_pending:
                self._resume_rerun = True
                return False
            self._resume_pending = True
            self._resumes_inflight += 1
        threading.Thread(target=self._resume, args=(additive,), daemon=True,
                         name="hvd-elastic-resume").start()
        return True

    def _resume(self, additive: bool) -> None:
        """Reshape the world after failure or scale-up (driver.py:304);
        loops while coalesced requests arrived mid-reshape."""
        closed_out = False
        try:
            while True:
                try:
                    self.wait_for_available_slots(self.min_np)
                except RuntimeError as e:
                    self.stop(error_message=str(e))
                    return
                if self._shutdown.is_set():
                    return
                self._activate_world()
                with self._lock:
                    if not self._resume_rerun:
                        # Close out ATOMICALLY with the rerun check: a
                        # request landing after this lock release sees
                        # pending=False and schedules its own resume.
                        # (Clearing rerun in a separate finally dropped a
                        # request that coalesced between the check and
                        # the finally — the silent-swallow this loop
                        # exists to prevent.)
                        self._resume_pending = False
                        self._resumes_inflight -= 1
                        closed_out = True
                        return
                    self._resume_rerun = False
        finally:
            if not closed_out:
                # stop/shutdown/exception paths: the job is ending (or the
                # driver stopped); dropping a pending rerun is correct.
                with self._lock:
                    self._resume_pending = False
                    self._resume_rerun = False
                    self._resumes_inflight -= 1

    # Back-compat spelling used in docs/tests.
    def resume(self, additive: bool = False) -> None:
        self.request_resume(additive=additive)


def _routable_self_addr() -> str:
    """Address remote workers can dial back to (driver_service.py NIC
    probing, simplified: hostname lookup with loopback fallback)."""
    try:
        addr = socket.gethostbyname(socket.gethostname())
        return addr
    except OSError:
        return "127.0.0.1"


def launch_elastic(args) -> int:
    """CLI entry for elastic runs (launch.py:689 _run_elastic analog)."""
    if not args.host_discovery_script:
        print("horovodrun: elastic mode requires --host-discovery-script",
              file=sys.stderr)
        return 2
    min_np = args.min_np or args.np or 1
    max_np = args.max_np or min_np
    discovery = HostDiscoveryScript(args.host_discovery_script,
                                    slots=args.slots)
    rendezvous = RendezvousServer(verbose=args.verbose)
    port = rendezvous.start()
    addr = _routable_self_addr()

    # Per-job coordinator base port when the whole (initial) world is
    # local: avoids collisions with orphaned workers of previous jobs
    # (launch.pick_coordinator_base_port; rank 0 = first local slot).
    # Costs one extra discovery-script invocation at startup — accepted:
    # the script must already be cheap enough for the periodic loop.
    try:
        from ..runner.launch import pick_coordinator_base_port, _is_local
        initial_hosts = discovery.find_available_hosts_and_slots()
        pick_coordinator_base_port(
            bool(initial_hosts) and
            all(_is_local(h) for h in initial_hosts))
    except Exception as e:
        get_logger().debug("coordinator port pick skipped: %s", e)

    from .launch_support import make_elastic_worker_fn
    driver = ElasticDriver(
        rendezvous, discovery, min_np, max_np,
        reset_limit=args.reset_limit,
        cooldown_range=tuple(args.blacklist_cooldown_range)
        if args.blacklist_cooldown_range else None,
        timeout=args.start_timeout or 600)
    worker_fn = make_elastic_worker_fn(args, addr, port, driver)
    driver.start(worker_fn)
    driver.join()
    if driver.error_message:
        print(f"horovodrun: {driver.error_message}", file=sys.stderr)
        return 1
    states = driver.registry.last_rank_states()
    failed = [k for k, v in states.items() if v == "FAILURE"]
    return 1 if failed else 0
