"""Elastic state objects: in-memory checkpoint + cross-rank sync.

Reference: horovod/common/elastic.py:26 (State: save/restore/sync +
reset-callback registry + ``check_host_updates`` raising
HostsUpdatedInterrupt), :116 (ObjectState), and the torch handlers
(torch/elastic/state.py:27-130: ModelStateHandler/OptimizerStateHandler
do in-memory save/restore and broadcast-based sync).

TPU build: ``ArrayState`` handles jax pytrees (params/optimizer state) —
commit copies to host memory (device_get), restore device_puts the last
commit, sync broadcasts from the new coordinator (rank 0) after a reset.
"""

from __future__ import annotations

import copy
import os
import pickle
import socket
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from ..exceptions import HostsUpdatedInterrupt
from .. import config as _config
from .. import functions as _functions


class State:
    """State representation for `hvd.elastic.run` (common/elastic.py:26).

    Subclasses implement save/restore/sync; users call ``commit()`` at safe
    points (typically every N batches) and the elastic loop calls
    ``restore()`` after a failure or ``sync()`` after a topology change."""

    def __init__(self, spill_dir: Optional[str] = None, **kwargs):
        self._reset_callbacks: List[Callable] = []
        self._host_messages = None  # set by the notification manager
        self._commit_seq = 0  # progress marker for the elastic retry bound
        # Disk spill: survives ABRUPT peer death, which the in-memory commit
        # cannot — a crashed peer FATALs every survivor's jax.distributed
        # client (TF coordination-service error propagation), so the only
        # copy of the last commit that outlives the process is one on disk.
        # The respawned incarnation picks it up via load_spill().
        self._spill_dir = spill_dir or os.environ.get(
            "HVD_TPU_ELASTIC_SPILL_DIR")

    def register_reset_callbacks(self, callbacks) -> None:
        """Callbacks invoked after world reset (re-jit, rebuild data sharding
        — common/elastic.py register_reset_callbacks)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def on_hosts_updated(self, updated_hosts, update_res) -> None:
        if self._host_messages is not None:
            self._host_messages.append((updated_hosts, update_res))

    def commit(self) -> None:
        """Checkpoint to memory (and to disk when spill is enabled) and
        check for host changes (common/elastic.py State.commit)."""
        self.save()
        self._commit_seq = getattr(self, "_commit_seq", 0) + 1
        self._spill()
        self.check_host_updates()

    # Disk spill ------------------------------------------------------------
    def _spill_path(self) -> Optional[str]:
        """Spill file keyed by (hostname, local_rank): stable across a full
        job restart even when global ranks are reshuffled by the new world
        (the post-restart ``sync()`` broadcast from rank 0 makes whichever
        copy the new rank 0 loaded authoritative)."""
        if not getattr(self, "_spill_dir", None):
            return None
        host = os.environ.get(_config.HOROVOD_HOSTNAME, socket.gethostname())
        local_rank = os.environ.get(_config.HOROVOD_LOCAL_RANK, "0")
        return os.path.join(self._spill_dir, f"state-{host}-{local_rank}.pkl")

    def _spill(self) -> None:
        path = self._spill_path()
        if path is None:
            return
        try:
            data = self._spill_payload()
        except NotImplementedError:
            # Custom State subclasses written against the original
            # save/restore/sync contract: degrade gracefully (warn once)
            # instead of failing the first commit() mid-training.
            if not getattr(self, "_spill_warned", False):
                self._spill_warned = True
                from ..utils import get_logger
                get_logger().warning(
                    "%s does not implement _spill_payload/"
                    "_load_spill_payload; disk spill is disabled for it "
                    "(implement both hooks to survive abrupt crashes)",
                    type(self).__name__)
            return
        try:
            os.makedirs(self._spill_dir, exist_ok=True)
            payload = {"seq": self._commit_seq, "data": data}
            # Atomic publish: a crash mid-pickle leaves the previous
            # commit's file intact (tmp + rename on the same filesystem).
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
        except Exception as e:
            # A full/unwritable spill directory — or an unpicklable state
            # attribute (PicklingError/TypeError) — must not kill the job
            # the spill exists to harden: the in-memory commit remains
            # valid, only crash-survival degrades.  Warn (throttled).
            now = time.time()
            if now - getattr(self, "_spill_err_ts", 0.0) > 60.0:
                self._spill_err_ts = now
                from ..utils import get_logger
                get_logger().warning(
                    "elastic spill to %s failed (%s); training continues "
                    "but a crash now loses progress since the last good "
                    "spill", path, e)

    def load_spill(self) -> bool:
        """Adopt a previous process incarnation's last on-disk commit if it
        is AHEAD of this object's in-memory commit.  Returns True when state
        was loaded (the caller should restore()/sync() afterwards).  Called
        automatically at ``hvd.elastic.run`` entry."""
        path = self._spill_path()
        if path is None or not os.path.exists(path):
            return False
        try:
            with open(path, "rb") as f:
                payload = pickle.load(f)
        except Exception:
            return False  # torn/corrupt file: fall back to in-memory state
        if payload.get("seq", 0) <= getattr(self, "_commit_seq", 0):
            return False
        try:
            self._load_spill_payload(payload["data"])
        except NotImplementedError:
            return False  # subclass without spill hooks (see _spill)
        self._commit_seq = payload["seq"]
        return True

    def clear_spill(self) -> None:
        """Remove the spill file (on successful training completion, so a
        LATER job reusing the directory does not resurrect stale state)."""
        path = self._spill_path()
        if path is not None and os.path.exists(path):
            try:
                os.remove(path)
            except OSError:
                pass

    def _spill_payload(self) -> Any:
        raise NotImplementedError

    def _load_spill_payload(self, data: Any) -> None:
        raise NotImplementedError

    def check_host_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when membership changed
        (common/elastic.py:83 check_host_updates)."""
        if self._host_messages is not None and self._host_messages:
            # skip_sync if only scale-up: HostManager encodes additive
            # updates as res == 2 and removals as res == 1.
            all_additive = all(res == 2 for _, res in self._host_messages)
            self._host_messages.clear()
            raise HostsUpdatedInterrupt(skip_sync=all_additive)

    # Subclass interface -----------------------------------------------------
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """State for arbitrary pickleable attributes (common/elastic.py:116
    ObjectState): attributes set via kwargs, saved/restored by deep copy,
    synced by rank-0 object broadcast."""

    def __init__(self, bcast_object=None, get_rank=None, spill_dir=None,
                 **kwargs):
        self._bcast_object = bcast_object or _functions.broadcast_object
        self._saved_state = dict(kwargs)
        self.__dict__.update(kwargs)
        super().__init__(spill_dir=spill_dir)

    def save(self) -> None:
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = copy.deepcopy(getattr(self, attr))
        self._saved_state = new_state

    def restore(self) -> None:
        self.__dict__.update(copy.deepcopy(self._saved_state))

    def sync(self) -> None:
        if self._saved_state:
            synced = self._bcast_object(self._saved_state, root_rank=0)
            self._saved_state = synced
            self.__dict__.update(
                {k: copy.deepcopy(v) for k, v in synced.items()})

    def _spill_payload(self):
        return self._saved_state

    def _load_spill_payload(self, data) -> None:
        self._saved_state = data
        self.restore()


class ArrayState(State):
    """State for jax pytrees (params, optimizer state) — the TPU analog of
    TorchState's ModelStateHandler/OptimizerStateHandler
    (torch/elastic/state.py:27-130)."""

    def __init__(self, spill_dir=None, **trees):
        self._trees: Dict[str, Any] = dict(trees)
        self._saved: Dict[str, Any] = {
            k: jax.device_get(v) for k, v in trees.items()}
        for k, v in trees.items():
            setattr(self, k, v)
        super().__init__(spill_dir=spill_dir)

    def save(self) -> None:
        """Commit to host memory (in-memory checkpoint, SURVEY.md §5.4)."""
        self._saved = {k: jax.device_get(getattr(self, k))
                       for k in self._trees.keys()}

    def restore(self) -> None:
        for k in self._trees.keys():
            setattr(self, k, jax.tree_util.tree_map(
                jax.numpy.asarray, self._saved[k]))

    def sync(self) -> None:
        """Broadcast current values from rank 0 (state.sync after
        re-rendezvous, common/elastic.py run_fn)."""
        for k in self._trees.keys():
            setattr(self, k, _functions.broadcast_variables(
                getattr(self, k), root_rank=0))

    def _spill_payload(self):
        return self._saved  # host-side numpy pytrees: directly pickleable

    def _load_spill_payload(self, data) -> None:
        self._saved = data
        self.restore()


class TpuState(ObjectState):
    """Combined convenience state: jax pytrees + plain Python attributes.

    hvd.elastic.TpuState(params=..., opt_state=..., epoch=0, batch=0) —
    the analog of hvd.elastic.TorchState(model, optimizer, epoch=..).
    """

    def __init__(self, bcast_object=None, spill_dir=None, **kwargs):
        self._array_keys = [k for k, v in kwargs.items()
                            if _is_pytree_of_arrays(v)]
        self._object_keys = [k for k in kwargs if k not in self._array_keys]
        self._arrays_saved = {}
        super().__init__(bcast_object=bcast_object, spill_dir=spill_dir,
                         **kwargs)
        self.save()

    def save(self) -> None:
        for k in self._array_keys:
            self._arrays_saved[k] = jax.device_get(getattr(self, k))
        new_state = {k: copy.deepcopy(getattr(self, k))
                     for k in self._object_keys}
        self._saved_state = new_state

    def restore(self) -> None:
        for k in self._array_keys:
            setattr(self, k, jax.tree_util.tree_map(
                jax.numpy.asarray, self._arrays_saved[k]))
        self.__dict__.update(copy.deepcopy(self._saved_state))

    def sync(self) -> None:
        for k in self._array_keys:
            setattr(self, k, _functions.broadcast_variables(
                getattr(self, k), root_rank=0))
        if self._object_keys:
            synced = self._bcast_object(
                {k: getattr(self, k) for k in self._object_keys},
                root_rank=0)
            self.__dict__.update(copy.deepcopy(synced))

    def _spill_payload(self):
        # _arrays_saved holds device_get'ed numpy pytrees; _saved_state
        # holds deep copies of plain attributes — both pickleable as-is.
        return {"arrays": self._arrays_saved, "objects": self._saved_state}

    def _load_spill_payload(self, data) -> None:
        self._arrays_saved = data["arrays"]
        self._saved_state = data["objects"]
        self.restore()


def _is_pytree_of_arrays(v) -> bool:
    leaves = jax.tree_util.tree_leaves(v)
    if not leaves:
        return False
    import numpy as np
    return all(isinstance(l, (jax.Array, np.ndarray)) for l in leaves)
