"""Worker exit bookkeeping.

Reference: horovod/runner/elastic/registration.py:28 WorkerStateRegistry —
gathers per-worker success/failure records and triggers the driver's
``resume()`` once the world needs reshaping.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: Optional[int] = None,
                 verbose: bool = False):
        self._driver = driver
        self._host_manager = host_manager
        self._lock = threading.Lock()
        self._states: Dict[Tuple[str, int], str] = {}
        self._reset_count = 0
        self._reset_limit = reset_limit
        self._barrier_size = 0

    @property
    def reset_count(self) -> int:
        return self._reset_count

    def reset(self, size: int) -> None:
        with self._lock:
            self._states = {}
            self._barrier_size = size

    def record_ready(self, host: str, slot: int, version: int = -1) -> None:
        self._record(host, slot, READY, version)

    def record_success(self, host: str, slot: int,
                       version: int = -1) -> None:
        self._record(host, slot, SUCCESS, version)

    def record_failure(self, host: str, slot: int,
                       version: int = -1) -> None:
        """Failure blacklists the host (driver.py:304 resume trigger).

        ``version`` is the world generation the worker was launched into;
        failures from a world that has already been reshaped past do not
        trigger another resume (all slots of a dead host coalesce into one
        reset, like the reference's per-reconfiguration counting).

        Reshape casualties are NOT blacklisted: on this runtime a world
        transition tears down the jax.distributed backend under live
        collectives, so workers of the outgoing world routinely die
        nonzero (shutdown-barrier aborts) through no fault of their host.
        A worker whose spawn world is already superseded, or that dies
        while a resume is pending/in flight, is such a casualty —
        blacklisting it (permanently, without --blacklist-cooldown-range)
        left single-host worlds unable to respawn after their own
        scale-up."""
        casualty = (0 <= version < self._driver.world_version) or \
            self._driver.resume_in_flight
        if not casualty:
            self._host_manager.blacklist.blacklist(host)
        self._record(host, slot, FAILURE, version)

    def _record(self, host: str, slot: int, state: str,
                version: int) -> None:
        with self._lock:
            self._states[(host, slot)] = state
        if state == FAILURE:
            if version >= 0 and version < self._driver.world_version:
                return  # stale world: already reshaped past this failure
            self._maybe_resume()

    def _maybe_resume(self) -> None:
        # request_resume coalesces concurrent requests (e.g. every slot of a
        # dead host failing at once) into ONE reshape; the reset limit counts
        # reshapes, matching the reference's world-reconfiguration semantics.
        scheduled = self._driver.request_resume()
        if not scheduled:
            return
        with self._lock:
            self._reset_count += 1
            over = self._reset_limit is not None and \
                self._reset_count > self._reset_limit
        if over:
            self._driver.stop(
                error_message=(
                    f"Reset limit of {self._reset_limit} reached "
                    f"(reference: --reset-limit semantics)"))

    def last_rank_states(self) -> Dict[Tuple[str, int], str]:
        with self._lock:
            return dict(self._states)
