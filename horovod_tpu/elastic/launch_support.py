"""Worker process launch for elastic runs (gloo_run.py:370 elastic variant).

Each worker process gets HOROVOD_ELASTIC=1 plus the rendezvous address; its
rank/size env reflects the slot at spawn time, but on re-rendezvous the
worker refreshes them from the KV store (elastic/__init__.py
_refresh_world_from_rendezvous) because ranks can change across resets.
HVD_TPU_WORLD_VERSION pins the world generation the worker was spawned
into, so post-reset refreshes can reject stale slot records.
"""

from __future__ import annotations

import os
import threading
from typing import Callable

from .. import config as _config
from ..runner import hosts as _hosts
from ..runner import safe_shell_exec
from ..runner.launch import env_from_args, _is_local, _ssh_command


def _coord_base() -> int:
    return int(os.environ.get("HVD_TPU_COORD_PORT", 29400))


def _coord_port(world_version: int) -> int:
    from . import coordinator_port_for
    return coordinator_port_for(_coord_base(), world_version)


def slot_env(slot: _hosts.SlotInfo, world_version: int, addr: str,
             port: int, driver, coord_base: int = None) -> dict:
    """The elastic worker protocol env for one slot incarnation — the ONE
    place the field set lives (the ssh launcher, ray_elastic and
    spark.elastic all spawn from it; a field added in only one spawner
    would make elastic workers silently disagree)."""
    from . import coordinator_port_for
    coord_base = coord_base if coord_base is not None else _coord_base()
    return {
        _config.HOROVOD_RANK: str(slot.rank),
        _config.HOROVOD_SIZE: str(slot.size),
        _config.HOROVOD_LOCAL_RANK: str(slot.local_rank),
        _config.HOROVOD_LOCAL_SIZE: str(slot.local_size),
        _config.HOROVOD_CROSS_RANK: str(slot.cross_rank),
        _config.HOROVOD_CROSS_SIZE: str(slot.cross_size),
        _config.HOROVOD_HOSTNAME: slot.hostname,
        _config.HOROVOD_RENDEZVOUS_ADDR: addr,
        _config.HOROVOD_RENDEZVOUS_PORT: str(port),
        "HOROVOD_ELASTIC": "1",
        "HVD_TPU_WORLD_VERSION": str(world_version),
        # Negotiation generation of the spawned world (matches the
        # survivors' post-refresh value — see elastic._reset).
        "HVD_TPU_NEGOTIATION_GEN": f"{world_version}.0",
        # Spawn-time discovery sequence: the notification manager
        # baselines here so pre-spawn updates are not replayed and
        # post-spawn ones are never missed.
        "HVD_TPU_DISCOVERY_SEQ": str(getattr(driver, "_update_seq", 0)),
        # Per-incarnation coordinator port (elastic/__init__.py
        # coordinator_port_for): every world reshape gets a FRESH
        # jax.distributed coordination service — reusing a live one
        # rejects reconnecting tasks ("different incarnation").
        "HVD_TPU_COORD_BASE": str(coord_base),
        "HVD_TPU_COORDINATOR":
            f"{addr}:{coordinator_port_for(coord_base, world_version)}",
    }


def make_elastic_worker_fn(args, addr: str, port: int, driver) -> Callable:
    base_env = dict(os.environ)
    base_env.update(env_from_args(args))

    def worker_fn(slot: _hosts.SlotInfo, terminate_event: threading.Event,
                  world_version: int):
        env = dict(base_env)
        env.update(slot_env(slot, world_version, addr, port, driver))
        prefix = f"[{slot.rank}]<stdout>:"
        cmd = args.command if _is_local(slot.hostname) else \
            _ssh_command(slot, args.command, env, args)
        return safe_shell_exec.execute(
            cmd, env=env, prefix=prefix,
            prefix_timestamp=getattr(args, "prefix_output_with_timestamp",
                                     False),
            events=[terminate_event])

    return worker_fn
