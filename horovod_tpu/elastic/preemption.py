"""TPU-VM preemption / maintenance-event handling (SURVEY.md §5.3's "TPU
equivalent" of failure detection).

Reference: horovod/runner/elastic/discovery.py:146 HostManager learns about
failed hosts AFTER they die (worker exit / discovery script).  On Cloud TPU
VMs the platform announces maintenance and preemption IN ADVANCE through
the per-VM metadata server (``instance/maintenance-event`` returns NONE
until an event is scheduled).  Handling the notice turns a crash recovery
(progress since the last commit lost) into a graceful drain: the condemned
host's workers commit at the next step, the world reshapes without them,
zero steps lost.

Split (mirrors the reference's worker-service/driver split):

* :class:`PreemptionSentinel` runs on each worker host — only the VM
  itself can reach its own metadata endpoint — polling the maintenance
  URL and publishing/clearing a ``{host}`` marker in the rendezvous KV
  scope ``preempt``.  Started by ``WorkerNotificationManager.init`` in
  elastic runs; URL overridable via ``HVD_TPU_MAINTENANCE_URL`` (tests
  point it at a mock server).
* :class:`PreemptionAwareDiscovery` wraps the driver's HostDiscovery and
  filters marked hosts out of the discovered set, so the ElasticDriver
  sees the host "removed" while it is still alive.  The driver gives
  workers on preempt-marked hosts a drain window (decommission semantics,
  driver.py ``_terminate_workers_on_lost_hosts``) instead of the
  immediate terminate a dead host gets.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, Optional, Set

from ..utils import get_logger
from .discovery import HostDiscovery

#: GCP metadata server; returns "NONE" or an event such as
#: "TERMINATE_ON_HOST_MAINTENANCE".  TPU VM preemption surfaces here and
#: via the ACPI shutdown signal; the metadata poll is the advance notice.
DEFAULT_METADATA_URL = ("http://metadata.google.internal/computeMetadata/"
                        "v1/instance/maintenance-event")

PREEMPT_SCOPE = "preempt"

#: How often an already-published marker is re-PUT (insurance against a KV
#: restart dropping it); between refreshes an active event costs no writes.
MARKER_REFRESH_S = 60.0


class PreemptionSentinel:
    """Worker-host daemon publishing this host's maintenance notice into
    the rendezvous KV (and clearing it if the event is cancelled)."""

    def __init__(self, client, hostname: Optional[str] = None,
                 url: Optional[str] = None,
                 poll_interval_s: Optional[float] = None):
        self.client = client
        # The marker must match the DRIVER's notion of this host (the
        # discovery script's names, stamped into HOROVOD_HOSTNAME by the
        # launcher) — gethostname() alone can differ (IP vs alias) and a
        # mismatched marker would silently disable the drain.
        self.host = hostname or os.environ.get("HOROVOD_HOSTNAME",
                                               socket.gethostname())
        self.url = url or os.environ.get("HVD_TPU_MAINTENANCE_URL",
                                         DEFAULT_METADATA_URL)
        self.poll_interval_s = poll_interval_s if poll_interval_s is not None \
            else float(os.environ.get("HVD_TPU_MAINTENANCE_POLL_S", "5"))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._marked = False
        self._marker_refresh_at = 0.0
        self._startup_reconciled = False
        from ..faultline import runtime as _flrt
        _flrt.maybe_install_from_env()

    def _poll_once(self) -> Optional[str]:
        """Current maintenance event, or None when the endpoint is
        unreachable (non-GCP hosts: treated as no notice)."""
        import urllib.request
        req = urllib.request.Request(
            self.url, headers={"Metadata-Flavor": "Google"})
        try:
            with urllib.request.urlopen(req, timeout=2) as resp:
                return resp.read().decode("utf-8", "replace").strip()
        except Exception as e:
            get_logger().debug("maintenance-event poll failed: %s", e)
            return None

    def step(self) -> None:
        """One poll + marker reconciliation (exposed for tests)."""
        event = self._poll_once()
        from ..faultline import runtime as _flrt
        plan = _flrt.PLAN
        if plan is not None:
            # ``preempt.poll`` injection point (marker publication): a
            # kill-rank fault makes this poll behave exactly as if the
            # metadata server announced maintenance — the marker goes out
            # through the real publish/refresh/clear state machine, so a
            # chaos run proves the whole notice→drain→clear→scale-up
            # loop, not a shortcut around it.  ONLY for plans that
            # exercise this point, an unreachable endpoint reads as
            # "NONE" (the hermetic chaos world has no metadata server;
            # without this substitution the cancelled event could never
            # clear its marker) — a plan poking other layers must not
            # convert a real metadata outage into a marker clear.
            fired = plan.fire("preempt.poll", self.host)
            if any(f.kind == "kill-rank" for f in fired):
                event = "FAULTLINE_PREEMPT"
            elif event is None and plan.targets_point("preempt.poll"):
                event = "NONE"
        if event and event != "NONE":
            if not self._marked:
                get_logger().warning(
                    "TPU maintenance notice on %s: %s — requesting "
                    "graceful drain", self.host, event)
            # Publish once, then only refresh occasionally (covers a KV
            # restart losing the marker): a re-PUT every poll for the
            # whole maintenance window is steady needless control-plane
            # write load.
            now = time.monotonic()
            if self._marked and now < self._marker_refresh_at:
                return
            try:
                self.client.put(PREEMPT_SCOPE, self.host, event.encode())
                self._marked = True
                self._marker_refresh_at = now + MARKER_REFRESH_S
            except Exception as e:
                # Retry next poll.  A failed INITIAL publish leaves _marked
                # False naturally; a failed REFRESH must NOT reset _marked —
                # the marker is still stored, and forgetting it would gate
                # off the clear branch and strand the marker (permanent
                # host exclusion) if the event later cancels.
                self._marker_refresh_at = now
                get_logger().warning("could not publish preemption "
                                     "marker: %s", e)
        elif event == "NONE" and (self._marked or
                                  not self._startup_reconciled):
            # Cancelled event — or a STALE marker left by a previous
            # incarnation of this host (its sentinel died with the drained
            # workers; only a live sentinel can clear the marker, so every
            # sentinel reconciles once at startup or the host could never
            # rejoin the pool).  The reconcile counts only when the delete
            # SUCCEEDS — a transient KV error here must retry next poll,
            # not silently leave the host excluded forever.
            try:
                self.client.delete(PREEMPT_SCOPE, self.host)
                if self._marked:
                    get_logger().info("maintenance notice on %s cleared",
                                      self.host)
                self._marked = False
                self._startup_reconciled = True
            except Exception as e:
                # Transient KV error: retry next poll.  Logged (never
                # silently dropped — hvdlint HVD009's swallowed-fault
                # antipattern): a string of these means the host stays
                # excluded, which an operator must be able to see.
                get_logger().debug(
                    "preemption marker clear failed on %s (retry next "
                    "poll): %s", self.host, e)
        elif event is not None:
            self._startup_reconciled = True

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="hvd-preempt-sentinel")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.step()
            self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()


class PreemptionAwareDiscovery(HostDiscovery):
    """Filters preempt-marked hosts out of the wrapped discovery's result
    so the ElasticDriver reshapes away from them before they die."""

    def __init__(self, inner: HostDiscovery,
                 marked_hosts_fn: Callable[[], Set[str]]):
        self.inner = inner
        self._marked_fn = marked_hosts_fn

    def marked_hosts(self) -> Set[str]:
        try:
            return set(self._marked_fn())
        except Exception as e:
            get_logger().debug("preemption marker read failed: %s", e)
            return set()

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        found = self.inner.find_available_hosts_and_slots()
        marked = self.marked_hosts()
        dropped = sorted(h for h in found if h in marked)
        if dropped:
            get_logger().info(
                "excluding preempt-marked host(s) %s from the "
                "discoverable world (graceful drain)", dropped)
        return {h: s for h, s in found.items() if h not in marked}
