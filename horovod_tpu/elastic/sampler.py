"""Elastic data sampler: mid-epoch-correct resume across world reshapes.

Reference: horovod/torch/elastic/sampler.py:24 ElasticSampler — shards a
deterministic epoch permutation across ranks and records how many samples
the WORLD has processed (``processed_num``, identical on every rank); on
reset (world size change) the remaining slice of the permutation is
re-sharded over the new world, so an elastic restart continues the epoch
instead of replaying it.  ``state_dict``/``load_state_dict`` ride
ObjectState/TpuState commits, and rank-0 sync is safe because the state is
rank-agnostic.
"""

from __future__ import annotations

import math
import random
from typing import Iterator, List, Optional


class ElasticSampler:
    """Index sampler over a sized dataset (sampler.py:24).

    Usage::

        sampler = hvd.elastic.ElasticSampler(len(dataset))
        state = hvd.elastic.TpuState(params=..., sampler=sampler.state_dict())
        state.register_reset_callbacks([lambda: (
            sampler.load_state_dict(state.sampler))])

        for batch_idx in range(len(sampler) // batch_size):
            idxs = sampler.get_indices(batch_idx, batch_size)
            ...train on dataset[idxs]...
            sampler.record_batch(batch_idx, batch_size)
            state.sampler = sampler.state_dict()
            state.commit()
        sampler.set_epoch(epoch + 1)   # AFTER the epoch (clears progress)
    """

    def __init__(self, dataset_or_size, shuffle: bool = True, seed: int = 0):
        self.dataset_size = (dataset_or_size if isinstance(dataset_or_size,
                                                           int)
                             else len(dataset_or_size))
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_num = 0
        self.rank = 0
        self.num_replicas = 1
        self.remaining_indices: List[int] = []
        self.num_samples = 0
        self.total_size = 0
        self.reset()

    def _world(self) -> tuple:
        from .. import core as _core
        if _core.is_initialized():
            return _core.rank(), _core.size()
        return self.rank, self.num_replicas

    def reset(self, rank: Optional[int] = None,
              size: Optional[int] = None) -> None:
        """Drop the first ``processed_num`` entries of the epoch permutation
        and re-shard the rest over the current world (sampler.py reset).
        ``rank``/``size`` override the live world for testing."""
        cur_rank, cur_size = self._world()
        self.rank = cur_rank if rank is None else rank
        self.num_replicas = max(cur_size if size is None else size, 1)
        all_indices = list(range(self.dataset_size))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(all_indices)
        self.remaining_indices = all_indices[self.processed_num:]
        self.num_samples = int(
            math.ceil(len(self.remaining_indices) / self.num_replicas))
        self.total_size = self.num_samples * self.num_replicas
        # This rank's shard, padded to equal length across ranks
        # (sampler.py __iter__ evenly-divisible padding).
        padded = self.remaining_indices + \
            self.remaining_indices[:self.total_size
                                   - len(self.remaining_indices)]
        self.indices = padded[self.rank:self.total_size:self.num_replicas]

    def set_epoch(self, epoch: int) -> None:
        """Start a new epoch permutation; call at the END of an epoch so a
        partially completed epoch keeps its progress (sampler.py
        set_epoch)."""
        self.epoch = epoch
        self.processed_num = 0
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """The world processed one more batch of ``batch_size`` per rank
        (sampler.py record_batch)."""
        self.processed_num += batch_size * self.num_replicas

    def get_indices(self, batch_idx: int, batch_size: int) -> List[int]:
        return self.indices[batch_idx * batch_size:
                            (batch_idx + 1) * batch_size]

    # -- state handoff (SamplerStateHandler, torch/elastic/state.py) --------

    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "processed_num": self.processed_num}

    def load_state_dict(self, state: dict) -> None:
        self.epoch = state["epoch"]
        self.processed_num = state["processed_num"]
        self.reset()

    # -- iteration -----------------------------------------------------------

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices)

    def __len__(self) -> int:
        return self.num_samples
