"""Host discovery + blacklist for elastic training.

Reference: horovod/runner/elastic/discovery.py:33 (HostDiscoveryScript:
runs the user's ``--host-discovery-script`` which prints "hostname:slots"
lines), :146 (HostManager: tracks current hosts, diffs updates, blacklists
failed hosts with an exponential cooldown range — blacklist cooldown from
``--blacklist-cooldown-range``).
"""

from __future__ import annotations

import random
import subprocess
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..utils import get_logger
from ..runner import hosts as _hosts


class HostDiscovery:
    """Interface (discovery.py HostDiscovery)."""

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs the user script; output lines "hostname:slots" or bare hostname
    (discovery.py:33 HostDiscoveryScript)."""

    def __init__(self, discovery_script: str, slots: Optional[int] = None):
        self.script = discovery_script
        self.default_slots = slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(self.script, shell=True,
                                      timeout=60).decode()
        result: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                result[host.strip()] = int(slots)
            else:
                result[line] = self.default_slots or 1
        return result


class FixedHostDiscovery(HostDiscovery):
    def __init__(self, hosts: Dict[str, int]):
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class Blacklist:
    """Failed-host blacklist with exponential cooldown
    (discovery.py CooldownBlacklist: base cooldown grows per repeat failure,
    bounded by the cooldown range)."""

    def __init__(self, cooldown_range: Optional[Tuple[float, float]] = None):
        self._cooldown_range = cooldown_range
        self._failures: Dict[str, int] = {}
        self._until: Dict[str, float] = {}
        self._since: Dict[str, float] = {}
        self._lock = threading.Lock()

    def blacklist(self, host: str) -> None:
        with self._lock:
            count = self._failures.get(host, 0) + 1
            self._failures[host] = count
            self._since[host] = time.time()
            if self._cooldown_range is None:
                self._until[host] = float("inf")
                return
            lo, hi = self._cooldown_range
            delay = min(hi, lo * (2 ** (count - 1)))
            delay *= 1.0 + 0.25 * random.random()  # jitter like the reference
            self._until[host] = time.time() + min(delay, hi)

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            until = self._until.get(host)
            if until is None:
                return False
            if time.time() >= until:
                del self._until[host]
                return False
            return True

    def blacklisted_since(self, host: str) -> float:
        with self._lock:
            return self._since.get(host, 0.0)

    def forgive(self, host: str) -> None:
        """Lift the entry (failure count is kept: a re-blacklist cools
        down longer)."""
        with self._lock:
            self._until.pop(host, None)

    def count(self, host: str) -> int:
        return self._failures.get(host, 0)


class HostManager:
    """Tracks the current host set, computes diffs against discovery output
    (discovery.py:146 HostManager)."""

    def __init__(self, discovery: HostDiscovery,
                 cooldown_range: Optional[Tuple[float, float]] = None):
        self.discovery = discovery
        self.blacklist = Blacklist(cooldown_range)
        self.current_hosts: Dict[str, int] = {}
        # Minimum slots the job needs (set by the ElasticDriver): the
        # blacklist-starvation escape keys off this, not off zero hosts.
        self.min_required = 1
        self._readmit_warned: Dict[str, float] = {}
        self._lock = threading.Lock()

    def update_available_hosts(self) -> int:
        """Refresh from discovery; returns change code: 0 = no change or
        pure scale-up, 1 = hosts removed (requires sync).  Mirrors the
        reference's HostUpdateResult semantics."""
        found_all = self.discovery.find_available_hosts_and_slots()
        found = {h: s for h, s in found_all.items()
                 if not self.blacklist.is_blacklisted(h)}
        if sum(found.values()) < self.min_required and \
                any(h not in found for h in found_all):
            # Pool starvation: the blacklist has pushed discoverable
            # capacity below what the job NEEDS (min_np).  A permanent
            # blacklist (no --blacklist-cooldown-range) would guarantee
            # job death — e.g. a reshape's shutdown-barrier abort killing
            # all of localhost's workers at once, or one genuine crash in
            # a pool with exactly min_np hosts.  Readmit least-recently-
            # blacklisted hosts until capacity suffices; --reset-limit
            # still bounds genuine crash loops.
            for h in sorted((h for h in found_all if h not in found),
                            key=self.blacklist.blacklisted_since):
                # Rate-limit per host: while capacity stays short this
                # branch re-fires every discovery poll, and a warning per
                # DISCOVER_INTERVAL_S is log spam, not signal.
                now = time.monotonic()
                if now - self._readmit_warned.get(h, -1e9) > 60.0:
                    self._readmit_warned[h] = now
                    get_logger().warning(
                        "discoverable capacity below minimum with hosts "
                        "blacklisted; readmitting %r (pool-starvation "
                        "escape, overrides a permanent blacklist — see "
                        "docs/knobs.md; --reset-limit still bounds crash "
                        "loops)", h)
                self.blacklist.forgive(h)
                found[h] = found_all[h]
                if sum(found.values()) >= self.min_required:
                    break
        with self._lock:
            prev = self.current_hosts
            removed = [h for h in prev if h not in found]
            added = [h for h in found if h not in prev]
            changed = [h for h in found
                       if h in prev and prev[h] != found[h]]
            self.current_hosts = found
        if removed or changed:
            return 1
        if added:
            return 2  # additive
        return 0

    def host_assignments(self, np_: int) -> List[_hosts.SlotInfo]:
        with self._lock:
            host_list = [_hosts.HostInfo(h, s)
                         for h, s in self.current_hosts.items()]
        return _hosts.get_host_assignments(host_list, np_, np_)

    @property
    def available_slots(self) -> int:
        with self._lock:
            return sum(self.current_hosts.values())
