"""Elastic training: fault tolerance + autoscaling.

Reference surface (horovod.elastic / hvd.elastic):

* ``hvd.elastic.run(train_fn)`` — retry wrapper (common/elastic.py:151
  run_fn): catches ``HorovodInternalError`` (failed collective → restore
  last commit + full reinit) and ``HostsUpdatedInterrupt`` (membership
  change → commit survives, reinit, optionally skip sync on pure scale-up).
* ``State`` / ``ObjectState`` / ``TpuState`` (state.py) — commit/restore/
  sync objects (TorchState analog).
* Driver side: ElasticDriver + discovery + WorkerStateRegistry (driver.py,
  discovery.py, registration.py), wired into ``horovodrun`` via
  ``--min-np/--max-np/--host-discovery-script/--reset-limit/
  --blacklist-cooldown-range``.

Reset on TPU: world-size changes force recompilation of every jitted
collective (SURVEY.md §7 "Elastic world-size changes") — the reset path
re-reads the slot record from the rendezvous KV store, re-initializes
``jax.distributed`` over the survivors, rebuilds the mesh, and the user's
reset callbacks re-jit; XLA's compilation cache hides most of the latency
for shapes seen before.
"""

from __future__ import annotations

import errno
import functools
import json
import os
import socket
import threading
import time
from typing import List, Optional

from ..exceptions import (HorovodInternalError, HostsUpdatedInterrupt,
                          RendezvousUnreachableError)
from ..utils import get_logger
from .. import config as _config
from .state import State, ObjectState, ArrayState, TpuState  # noqa: F401
from .sampler import ElasticSampler  # noqa: F401
from .driver import ElasticDriver  # noqa: F401
from .discovery import (  # noqa: F401
    HostDiscovery, HostDiscoveryScript, FixedHostDiscovery, HostManager)
from .preemption import (  # noqa: F401
    PreemptionAwareDiscovery, PreemptionSentinel)


class WorkerNotificationManager:
    """Worker-side host-update listener.

    Reference: horovod/runner/elastic/worker.py:46 WorkerNotificationService
    (socket RPC per worker).  Here: a daemon thread polls the rendezvous KV
    key ``discovery/update``; on version bump every registered State gets
    ``on_hosts_updated`` so its next ``commit()`` raises
    HostsUpdatedInterrupt."""

    def __init__(self):
        self._listeners: List[State] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seen_version = 0
        self._lock = threading.Lock()
        self._sentinel = None

    def init(self):
        if self._thread is not None:
            return
        addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
        port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
        if not addr or not port or \
                os.environ.get("HOROVOD_ELASTIC") != "1":
            return  # not an elastic run: no-op manager
        from ..runner.http_server import KVStoreClient
        client = KVStoreClient(addr, int(port))
        # Baseline the discovery sequence: updates that predate this worker
        # are already reflected in the world it was spawned into — replaying
        # them would raise a spurious HostsUpdatedInterrupt and strand the
        # worker waiting for a world version that never comes.  The driver
        # stamps the spawn-time sequence into the env
        # (HVD_TPU_DISCOVERY_SEQ), closing the spawn→init race; the KV read
        # is the fallback for workers launched by other paths.
        spawn_seq = os.environ.get("HVD_TPU_DISCOVERY_SEQ")
        if spawn_seq is not None:
            self._seen_version = int(spawn_seq)
        else:
            for attempt in range(3):
                try:
                    raw = client.get("discovery", "update")
                    if raw:
                        self._seen_version = json.loads(raw).get("version", 0)
                    break
                except Exception as e:
                    get_logger().warning(
                        "discovery baseline read failed (attempt %d): %s",
                        attempt + 1, e)
                    time.sleep(0.2)

        def poll():
            while not self._stop.is_set():
                try:
                    raw = client.get("discovery", "update")
                    if raw:
                        rec = json.loads(raw)
                        if rec["version"] > self._seen_version:
                            self._seen_version = rec["version"]
                            with self._lock:
                                for st in self._listeners:
                                    st.on_hosts_updated(rec.get("hosts"),
                                                        rec.get("res", 1))
                except Exception as e:
                    get_logger().debug("notification poll failed: %s", e)
                self._stop.wait(1.0)

        self._thread = threading.Thread(target=poll, daemon=True,
                                        name="hvd-worker-notify")
        self._thread.start()
        # TPU-VM preemption sentinel: polls this host's metadata
        # maintenance-event endpoint and publishes a drain marker the
        # driver's PreemptionAwareDiscovery consumes (elastic/preemption.py).
        # Cheap (one 2 s-timeout HTTP poll per 5 s, a fast failure off
        # GCP); disable with HVD_TPU_PREEMPTION_SENTINEL=0.
        if os.environ.get("HVD_TPU_PREEMPTION_SENTINEL", "1") == "1":
            from .preemption import PreemptionSentinel
            self._sentinel = PreemptionSentinel(client)
            self._sentinel.start()

    def register_listener(self, state: State):
        with self._lock:
            if state._host_messages is None:
                state._host_messages = []
            self._listeners.append(state)

    def remove_listener(self, state: State):
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)


notification_manager = WorkerNotificationManager()


class _RendezvousLiveness:
    """Latches sustained transport-dead signals from the launcher's KV
    store and raises ``RendezvousUnreachableError`` after
    ``HVD_TPU_RENDEZVOUS_DEAD_S`` (default 30 s) without one successful
    request.  Dead signals are refused/reset connections, connect/read
    timeouts, and host/network-unreachable errnos — a launcher process
    death (RST) and a launcher HOST death (preempted VM, partition: no
    RST, just timeouts) both qualify.  HTTP-status ``OSError``s raised by
    the client for >=400 responses do NOT: the server answered, so it is
    alive.  Polling loops call ``ok()`` after any successful request and
    ``note(e)`` in their retry handler."""

    _DEAD_ERRNOS = {errno.EHOSTUNREACH, errno.ENETUNREACH,
                    errno.ECONNABORTED}

    def __init__(self, addr, port):
        self.addr, self.port = addr, port
        self.window = float(
            os.environ.get("HVD_TPU_RENDEZVOUS_DEAD_S", "30"))
        self._since = None

    def ok(self) -> None:
        self._since = None

    def note(self, e: BaseException) -> bool:
        """Record an error; True if it was a transport-dead signal.
        Raises RendezvousUnreachableError once signals have been sustained
        for the window."""
        dead = isinstance(e, (ConnectionRefusedError, ConnectionResetError,
                              BrokenPipeError, TimeoutError)) or \
            (isinstance(e, OSError) and e.errno in self._DEAD_ERRNOS)
        if not dead:
            return False
        now = time.monotonic()  # fatal verdict: immune to clock steps
        self._since = self._since or now
        if now - self._since > self.window:
            raise RendezvousUnreachableError(
                f"rendezvous {self.addr}:{self.port} unreachable for "
                f"{self.window:.0f}s — launcher presumed dead") from e
        return True


def _refresh_world_from_rendezvous(allow_same_world: bool = False) -> str:
    """After a reset, fetch this worker's new slot record keyed by
    (hostname, local_rank) from the rendezvous KV store and refresh the
    HOROVOD_* env (the gloo elastic re-rendezvous pattern,
    runner/http/http_server.py elastic handler).  Returns "refreshed"
    when a NEW world's slot was adopted, "same_world" on the
    allow_same_world fallback below.

    Version gate: the KV store still holds the previous world's records
    while the driver reshapes; we wait for a world version strictly newer
    than the one we left (HVD_TPU_WORLD_VERSION) and a slot record stamped
    with that version.

    ``allow_same_world``: the retry loop escalates repeated in-place reset
    failures to a world refresh on the ASSUMPTION the world changed under
    us — but when it did not (transient churn: a peer wedged in a timing-
    out collective), waiting for a strictly newer version deadlocks until
    the elastic timeout while live peers train on.  With this flag, if no
    newer world appears within a bounded window and the CURRENT world
    still lists this worker's slot, return "same_world" so the caller
    falls back to an in-place (generation-bump) reset instead."""
    addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
    port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
    if not addr or not port:
        return "refreshed"
    from ..runner.http_server import KVStoreClient
    client = KVStoreClient(addr, int(port))
    hostname = os.environ.get(_config.HOROVOD_HOSTNAME, socket.gethostname())
    local_rank = os.environ.get(_config.HOROVOD_LOCAL_RANK, "0")
    last_version = int(os.environ.get("HVD_TPU_WORLD_VERSION", "0"))
    deadline = time.time() + float(
        os.environ.get(_config.HOROVOD_ELASTIC_TIMEOUT, "600"))
    same_world_after = time.time() + float(
        os.environ.get("HVD_TPU_SAME_WORLD_FALLBACK_S", "20"))
    scaled_out_since = None
    liveness = _RendezvousLiveness(addr, port)
    while time.time() < deadline:
        try:
            world_raw = client.get("rendezvous", "world")
            liveness.ok()
            world = json.loads(world_raw) if world_raw else {"version": 0}
            if allow_same_world and time.time() > same_world_after and \
                    world.get("version", 0) == last_version:
                raw = client.get("rendezvous",
                                 f"slot/{hostname}/{local_rank}")
                rec = json.loads(raw) if raw else {}
                if rec.get("version", -1) == last_version:
                    get_logger().info(
                        "elastic: world unchanged (v%d) and slot still "
                        "valid — falling back to in-place reset",
                        last_version)
                    return "same_world"
            if world.get("version", 0) > last_version:
                raw = client.get("rendezvous",
                                 f"slot/{hostname}/{local_rank}")
                rec = json.loads(raw) if raw else {}
                if rec.get("version", 0) != world["version"]:
                    # A new world exists and this (host, local_rank) has no
                    # slot in it: we were scaled out.  Exit GRACEFULLY —
                    # the driver records a decommission, not a failure, and
                    # an abrupt death here would FATAL the survivors'
                    # jax.distributed clients.  Short grace window in case
                    # the driver is mid-publication of yet another world.
                    if scaled_out_since is None:
                        scaled_out_since = time.time()
                    elif time.time() - scaled_out_since > 5.0:
                        get_logger().info(
                            "elastic: no slot for (%s, %s) in world v%s — "
                            "scaled out, exiting", hostname, local_rank,
                            world["version"])
                        # Leave the coordination service NOW (bounded):
                        # the surviving ranks' resets are waiting at the
                        # old runtime's shutdown barrier, which needs
                        # every task — exiting without this made them
                        # burn the barrier deadline and F-abort whenever
                        # this worker was slow to die.
                        try:
                            import jax
                            from jax._src import distributed as _jd
                            if getattr(_jd.global_state, "client",
                                       None) is not None:
                                jax.distributed.shutdown()
                        except Exception as e:
                            get_logger().debug(
                                "scaled-out jax shutdown: %s", e)
                        raise SystemExit(0)
                else:
                    os.environ[_config.HOROVOD_RANK] = str(rec["rank"])
                    os.environ[_config.HOROVOD_SIZE] = str(rec["size"])
                    os.environ[_config.HOROVOD_LOCAL_RANK] = \
                        str(rec["local_rank"])
                    os.environ[_config.HOROVOD_LOCAL_SIZE] = \
                        str(rec["local_size"])
                    os.environ[_config.HOROVOD_CROSS_RANK] = \
                        str(rec["cross_rank"])
                    os.environ[_config.HOROVOD_CROSS_SIZE] = \
                        str(rec["cross_size"])
                    os.environ["HVD_TPU_WORLD_VERSION"] = \
                        str(rec["version"])
                    return "refreshed"
        except SystemExit:
            raise
        except Exception as e:
            # A dead launcher means no world to rejoin: fail fast rather
            # than polling out the full elastic timeout (note() raises
            # RendezvousUnreachableError on sustained transport death).
            liveness.note(e)
            get_logger().debug("rendezvous refresh retry: %s", e)
        time.sleep(0.5)
    raise HorovodInternalError(
        "timed out waiting for a slot assignment after reset")


def _await_world_at_init_barrier() -> None:
    """Block until EVERY member incarnation of this world generation is
    alive at this barrier — only then is it safe to enter
    ``jax.distributed.initialize``.

    Why: a non-converging initialize is not a catchable error — the
    coordination client ABORTS the process on the RegisterTask deadline
    (client.h:80).  Without a pre-init rendezvous, respawned incarnations
    enter initialize at offset times, each abort triggers another driver
    reshape (new world version, new coordinator port), and the world
    livelocks with alternating single-sided aborts.  Parking incarnations
    HERE (pure KV polling, no coordination client) until the full member
    set of the CURRENT generation is present makes the post-crash cycle
    converge: the last respawn unblocks everyone simultaneously.

    Presence keys are scoped by WORLD VERSION and carry the same-world
    reset counter ``c`` of the rank's generation "w.c" as their value.
    The barrier completes only when every rank of the version is present
    AT THE SAME ``c`` — and ranks converge on one ``c`` by max-merge:
    in-place resets are not synchronized (one rank may have failed and
    bumped several times before its peer's collective even times out),
    so a rank that sees a LARGER counter announced adopts it (gen +
    coordinator port) instead of waiting forever at its own.  If the
    world is superseded while waiting (version moved past ours — our
    spawn world died), the worker adopts its new slot record and
    re-announces under the new version; a worker with no slot in the new
    world exits gracefully via ``_refresh_world_from_rendezvous``.

    Key lifetime: presence keys persist after the barrier completes —
    safe because the driver bumps the world version on EVERY respawn
    (record_failure → resume → _activate_world version++), so a fresh
    incarnation always rendezvouses under a version whose keys only its
    own world wrote; a completed version's keys are never consulted
    again.  External launchers that respawn without a version bump would
    need incarnation-stamped values here."""
    addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
    port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
    if not addr or not port or os.environ.get("HOROVOD_ELASTIC") != "1":
        return
    from ..runner.http_server import KVStoreClient
    client = KVStoreClient(addr, int(port))
    deadline = time.time() + float(
        os.environ.get(_config.HOROVOD_ELASTIC_TIMEOUT, "600"))
    announced = None  # (version, c) last published
    liveness = _RendezvousLiveness(addr, port)

    def _set_gen(w: int, c: int) -> None:
        os.environ["HVD_TPU_NEGOTIATION_GEN"] = f"{w}.{c}"
        coord = _coordinator_for_gen(f"{w}.{c}")
        if coord:
            os.environ["HVD_TPU_COORDINATOR"] = coord

    while time.time() < deadline:
        my_version = int(os.environ.get("HVD_TPU_WORLD_VERSION", "0"))
        gen = os.environ.get("HVD_TPU_NEGOTIATION_GEN", f"{my_version}.0")
        w, _, c = gen.partition(".")
        my_c = int(c or 0)
        rank = int(os.environ.get(_config.HOROVOD_RANK, "0"))
        size = int(os.environ.get(_config.HOROVOD_SIZE, "1"))
        if size <= 1:
            return  # no peers to meet
        try:
            if announced != (my_version, my_c):
                client.put("initbar", f"{my_version}/{rank}",
                           str(my_c).encode())
                announced = (my_version, my_c)
            raw = client.get("rendezvous", "world")
            liveness.ok()
            world = json.loads(raw) if raw else {}
            if world.get("version", my_version) > my_version:
                # Spawn world superseded: adopt the new world's slot for
                # this (host, local_rank) and re-announce under it.
                _refresh_world_from_rendezvous()
                _set_gen(int(os.environ.get("HVD_TPU_WORLD_VERSION", "0")),
                         0)
                continue
            # One scope scan per poll (O(1) requests per rank per tick;
            # per-key GETs would put O(size²) load on the KV during init).
            bar = client.scan("initbar")
            counters = [int(v) for k, v in bar.items()
                        if k.startswith(f"{my_version}/")
                        and int(k.rsplit("/", 1)[1]) < size]
            cmax = max(counters + [my_c])
            if cmax > my_c:
                get_logger().info(
                    "elastic: init barrier adopting generation %d.%d "
                    "(peer reset further than us)", my_version, cmax)
                _set_gen(my_version, cmax)
                continue
            if len(counters) >= size and \
                    all(cc == cmax for cc in counters):
                return
        except HorovodInternalError:
            raise
        except Exception as e:
            liveness.note(e)
            get_logger().debug("init barrier poll failed: %s", e)
        time.sleep(0.2)
    raise HorovodInternalError(
        "timed out waiting for world members at the init barrier")


def coordinator_port_for(base: int, world_version: int,
                         reset_count: int = 0) -> int:
    """Coordinator port for a world incarnation: a fresh jax.distributed
    coordination service per (world, same-world reset) — the TF
    coordination service rejects a task reconnecting to a live service
    with a new incarnation id, so every reshape/recovery must bind a new
    port.  All ranks derive the same value from the same generation; the
    SAME formula feeds freshly spawned workers (launch_support,
    ray_elastic) and surviving workers (_reset)."""
    return int(base) + (int(world_version) * 16 + int(reset_count)) % 2000


def _coordinator_for_gen(gen: str) -> Optional[str]:
    """Coordinator address for a negotiation generation "w.c" (see
    coordinator_port_for)."""
    base = os.environ.get("HVD_TPU_COORD_BASE")
    cur = os.environ.get("HVD_TPU_COORDINATOR")
    if not base or not cur:
        return None
    host = cur.rsplit(":", 1)[0]
    w, _, c = gen.partition(".")
    return f"{host}:{coordinator_port_for(int(base), int(w), int(c or 0))}"


def _mark_elastic(phase: str, detail: str = "") -> None:
    """ELASTIC timeline instant around the scale-down/scale-up barriers
    (timeline.elastic_event): a post-mortem trace of a wedged or slow
    reset shows WHERE the world change stalled — before the old
    runtime's shutdown or waiting at the new world's init barrier.
    Emitted into whatever timeline is live; never raises (a closed or
    absent timeline must not perturb a reset)."""
    try:
        from .. import core as _core
        tl = _core._state.timeline
        if tl is not None:
            tl.elastic_event(
                phase,
                int(os.environ.get("HVD_TPU_WORLD_VERSION", "0") or 0),
                detail)
    except Exception:  # pragma: no cover - instrumentation only
        pass


def _reset(refresh_world: bool = True,
           allow_same_world: bool = False) -> None:
    """Full reinit: shutdown the runtime, re-rendezvous, re-init
    (common/elastic.py run_fn 'reinit' = shutdown + re-rendezvous).

    ``refresh_world=False`` for recovery from a collective failure with
    UNCHANGED membership (HorovodInternalError): every rank received the
    same error verdict and resets simultaneously into the same world, so
    there is no new world version to wait for — the slot env is still
    valid and only the JAX runtime needs rebuilding."""
    from .. import core as _core
    # Instant BEFORE shutdown — the old timeline is still alive here.
    _mark_elastic("reset", "refresh-world" if refresh_world
                  else "same-world reinit")
    _core.shutdown()
    if os.environ.get("HOROVOD_ELASTIC") == "1":
        if refresh_world:
            outcome = _refresh_world_from_rendezvous(
                allow_same_world=allow_same_world)
            if outcome == "same_world":
                refresh_world = False  # fall through to the gen-bump path
            else:
                # New world: generation = (world_version, 0).  Newly
                # spawned workers get the same value from the driver
                # (launch_support), so every member of the new world
                # scopes its negotiation keys identically.
                os.environ["HVD_TPU_NEGOTIATION_GEN"] = \
                    f"{os.environ.get('HVD_TPU_WORLD_VERSION', '0')}.0"
                coord = _coordinator_for_gen(
                    os.environ["HVD_TPU_NEGOTIATION_GEN"])
                if coord:
                    os.environ["HVD_TPU_COORDINATOR"] = coord
        if not refresh_world:
            # Same world, in-place recovery: every rank received the same
            # collective-failure verdict and resets together — bump the
            # same-world counter so the fresh negotiators never consume the
            # previous incarnation's KV records.
            cur = os.environ.get("HVD_TPU_NEGOTIATION_GEN", "0.0")
            w, _, c = cur.partition(".")
            os.environ["HVD_TPU_NEGOTIATION_GEN"] = \
                f"{w}.{int(c or 0) + 1}"
            coord = _coordinator_for_gen(
                os.environ["HVD_TPU_NEGOTIATION_GEN"])
            if coord:
                os.environ["HVD_TPU_COORDINATOR"] = coord
        import jax
        try:
            from jax._src import distributed as _jdist
            if getattr(_jdist.global_state, "client", None) is not None:
                jax.distributed.shutdown()
        except Exception as e:
            # A dead coordinator makes shutdown raise; the clear below still
            # severs this process from the stale runtime.
            get_logger().warning("jax.distributed shutdown failed: %s", e)
        try:
            # A world-size change needs a fresh multi-process runtime: the
            # backend was initialized for the OLD world, and
            # jax.distributed.initialize refuses to run on a live backend.
            # Dropping the backends forces re-initialization (and re-traces
            # every compiled step — the recompilation cost SURVEY.md §7
            # flags as inherent to elastic world changes).  Failure here
            # must be FATAL: continuing would silently reuse the old world's
            # runtime against the new world's env and hang collectives.
            from jax._src import api as _jax_api
            _jax_api.clear_backends()
        except Exception as e:
            raise HorovodInternalError(
                f"failed to reset the JAX backend for the new world: {e}"
            ) from e
    _core.init()
    # Instant AFTER re-init — lands in the NEW world's timeline, so a
    # merged trace shows the reset/world pair bracketing the barrier.
    _mark_elastic(
        "world",
        f"gen={os.environ.get('HVD_TPU_NEGOTIATION_GEN', '0.0')}")


def run(func):
    """Elastic retry decorator (hvd.elastic.run, common/elastic.py:151).

    Usage::

        state = hvd.elastic.TpuState(params=params, opt_state=opt_state,
                                     epoch=0)

        @hvd.elastic.run
        def train(state):
            for epoch in range(state.epoch, 90):
                ...train...
                state.epoch = epoch
                state.commit()

        train(state)
    """
    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        notification_manager.init()
        notification_manager.register_listener(state)
        # Crash survival: if a previous incarnation of this worker spilled
        # a commit to disk (HVD_TPU_ELASTIC_SPILL_DIR) that is ahead of the
        # freshly constructed state, adopt it.  The first-iteration sync()
        # then broadcasts rank 0's adopted values so the new world agrees.
        if state.load_spill():
            get_logger().info(
                "elastic: resumed from on-disk spill (commit seq %d)",
                state._commit_seq)
        skip_sync = False
        reset_required = False
        refresh_world = True
        escalated = False
        reset_failures = 0
        no_progress_failures = 0
        try:
            while True:
                if reset_required and not refresh_world:
                    # In-place recovery assumes UNCHANGED membership; a
                    # pending host update (e.g. the failure was a peer
                    # being decommissioned) means the world DID change and
                    # re-initializing into the stale env would hang — take
                    # the refresh path instead.
                    try:
                        state.check_host_updates()
                    except HostsUpdatedInterrupt as e:
                        skip_sync = e.skip_sync
                        refresh_world = True
                        escalated = False  # confirmed membership change
                if reset_required:
                    try:
                        # The driver only notifies when a reshape IS
                        # coming (no-op additive discoveries are
                        # suppressed, driver.py _discover_loop), so the
                        # interrupt path waits for the new version rather
                        # than racing it with an in-place fallback — a
                        # premature same-world reset during a real
                        # scale-up strands the new worker at the init
                        # barrier.  escalated=True marks refreshes adopted
                        # on the retry heuristic (not a confirmed host
                        # change): those may fall back to in-place when
                        # the world version never actually moved.
                        _reset(refresh_world=refresh_world,
                               allow_same_world=escalated)
                    except Exception as e:
                        # Re-init can fail transiently while the new world
                        # is still assembling (jax.distributed barrier or
                        # gloo context timeouts): retry the reset, letting
                        # the top-of-loop host-update check upgrade to a
                        # world refresh when membership changed again.
                        import jax as _jax
                        if not isinstance(e, (HorovodInternalError,
                                              _jax.errors.JaxRuntimeError)):
                            raise
                        if isinstance(e, RendezvousUnreachableError):
                            raise  # no launcher → no world to rejoin
                        reset_failures += 1
                        if reset_failures >= 6:
                            # A dead launcher/rendezvous makes every reset
                            # time out; re-raise so the worker terminates
                            # instead of looping timeout/warn forever.
                            raise
                        get_logger().warning(
                            "elastic: reset failed (%s); retrying "
                            "(%d/5)", e, reset_failures)
                        if reset_failures >= 3:
                            # Same-world retries keep failing: assume the
                            # world DID change under us and wait for a new
                            # version (bounded — _reset falls back to
                            # in-place if the version never moves).
                            refresh_world = True
                            escalated = True
                        time.sleep(1.0)
                        continue
                    reset_failures = 0
                    escalated = False
                    # Restore AFTER the backend reset: the in-memory commit
                    # holds host (numpy) copies, so restore re-materializes
                    # arrays on the NEW backend.  (Restoring before the
                    # reset would leave State attributes pointing at deleted
                    # buffers of the old backend.)  On the interrupt path
                    # this equals the current values: commit() saved
                    # immediately before raising.
                    state.restore()
                    state.on_reset()
                seq_before = getattr(state, "_commit_seq", 0)
                try:
                    if not skip_sync:
                        state.sync()
                    result = func(state, *args, **kwargs)
                    # Completed: drop the spill so a later job reusing the
                    # directory does not resurrect this run's final state.
                    state.clear_spill()
                    return result
                except HorovodInternalError as e:
                    # Progress bound: a DETERMINISTIC failure (e.g. a
                    # device OOM surfacing through the collective error
                    # mapping) would otherwise restore-and-retry forever on
                    # the in-place path, invisible to --reset-limit.  Any
                    # committed progress between failures resets the count.
                    if getattr(state, "_commit_seq", 0) > seq_before:
                        no_progress_failures = 1
                    else:
                        no_progress_failures += 1
                    if no_progress_failures > 5:
                        raise
                    get_logger().info(
                        "elastic: collective failure (%s) — restoring last "
                        "commit", e)
                    skip_sync = False
                    refresh_world = False  # membership unchanged
                    escalated = False
                except HostsUpdatedInterrupt as e:
                    get_logger().info(
                        "elastic: host membership changed — reinitializing")
                    skip_sync = e.skip_sync
                    refresh_world = True
                    escalated = False
                reset_required = True
        finally:
            notification_manager.remove_listener(state)

    return wrapper
