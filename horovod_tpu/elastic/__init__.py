"""Elastic training: fault tolerance + autoscaling.

Reference surface (horovod.elastic / hvd.elastic):

* ``hvd.elastic.run(train_fn)`` — retry wrapper (common/elastic.py:151
  run_fn): catches ``HorovodInternalError`` (failed collective → restore
  last commit + full reinit) and ``HostsUpdatedInterrupt`` (membership
  change → commit survives, reinit, optionally skip sync on pure scale-up).
* ``State`` / ``ObjectState`` / ``TpuState`` (state.py) — commit/restore/
  sync objects (TorchState analog).
* Driver side: ElasticDriver + discovery + WorkerStateRegistry (driver.py,
  discovery.py, registration.py), wired into ``horovodrun`` via
  ``--min-np/--max-np/--host-discovery-script/--reset-limit/
  --blacklist-cooldown-range``.

Reset on TPU: world-size changes force recompilation of every jitted
collective (SURVEY.md §7 "Elastic world-size changes") — the reset path
re-reads the slot record from the rendezvous KV store, re-initializes
``jax.distributed`` over the survivors, rebuilds the mesh, and the user's
reset callbacks re-jit; XLA's compilation cache hides most of the latency
for shapes seen before.
"""

from __future__ import annotations

import functools
import json
import os
import socket
import threading
import time
from typing import List, Optional

from ..exceptions import HorovodInternalError, HostsUpdatedInterrupt
from ..utils import get_logger
from .. import config as _config
from .state import State, ObjectState, ArrayState, TpuState  # noqa: F401
from .driver import ElasticDriver  # noqa: F401
from .discovery import (  # noqa: F401
    HostDiscovery, HostDiscoveryScript, FixedHostDiscovery, HostManager)


class WorkerNotificationManager:
    """Worker-side host-update listener.

    Reference: horovod/runner/elastic/worker.py:46 WorkerNotificationService
    (socket RPC per worker).  Here: a daemon thread polls the rendezvous KV
    key ``discovery/update``; on version bump every registered State gets
    ``on_hosts_updated`` so its next ``commit()`` raises
    HostsUpdatedInterrupt."""

    def __init__(self):
        self._listeners: List[State] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._seen_version = 0
        self._lock = threading.Lock()

    def init(self):
        if self._thread is not None:
            return
        addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
        port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
        if not addr or not port or \
                os.environ.get("HOROVOD_ELASTIC") != "1":
            return  # not an elastic run: no-op manager
        from ..runner.http_server import KVStoreClient
        client = KVStoreClient(addr, int(port))
        # Baseline the discovery sequence: updates that predate this worker
        # are already reflected in the world it was spawned into — replaying
        # them would raise a spurious HostsUpdatedInterrupt and strand the
        # worker waiting for a world version that never comes.  The driver
        # stamps the spawn-time sequence into the env
        # (HVD_TPU_DISCOVERY_SEQ), closing the spawn→init race; the KV read
        # is the fallback for workers launched by other paths.
        spawn_seq = os.environ.get("HVD_TPU_DISCOVERY_SEQ")
        if spawn_seq is not None:
            self._seen_version = int(spawn_seq)
        else:
            for attempt in range(3):
                try:
                    raw = client.get("discovery", "update")
                    if raw:
                        self._seen_version = json.loads(raw).get("version", 0)
                    break
                except Exception as e:
                    get_logger().warning(
                        "discovery baseline read failed (attempt %d): %s",
                        attempt + 1, e)
                    time.sleep(0.2)

        def poll():
            while not self._stop.is_set():
                try:
                    raw = client.get("discovery", "update")
                    if raw:
                        rec = json.loads(raw)
                        if rec["version"] > self._seen_version:
                            self._seen_version = rec["version"]
                            with self._lock:
                                for st in self._listeners:
                                    st.on_hosts_updated(rec.get("hosts"),
                                                        rec.get("res", 1))
                except Exception as e:
                    get_logger().debug("notification poll failed: %s", e)
                self._stop.wait(1.0)

        self._thread = threading.Thread(target=poll, daemon=True,
                                        name="hvd-worker-notify")
        self._thread.start()

    def register_listener(self, state: State):
        with self._lock:
            if state._host_messages is None:
                state._host_messages = []
            self._listeners.append(state)

    def remove_listener(self, state: State):
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)


notification_manager = WorkerNotificationManager()


def _refresh_world_from_rendezvous() -> None:
    """After a reset, fetch this worker's new slot record keyed by
    (hostname, local_rank) from the rendezvous KV store and refresh the
    HOROVOD_* env (the gloo elastic re-rendezvous pattern,
    runner/http/http_server.py elastic handler).

    Version gate: the KV store still holds the previous world's records
    while the driver reshapes; we wait for a world version strictly newer
    than the one we left (HVD_TPU_WORLD_VERSION) and a slot record stamped
    with that version."""
    addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
    port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
    if not addr or not port:
        return
    from ..runner.http_server import KVStoreClient
    client = KVStoreClient(addr, int(port))
    hostname = os.environ.get(_config.HOROVOD_HOSTNAME, socket.gethostname())
    local_rank = os.environ.get(_config.HOROVOD_LOCAL_RANK, "0")
    last_version = int(os.environ.get("HVD_TPU_WORLD_VERSION", "0"))
    deadline = time.time() + float(
        os.environ.get(_config.HOROVOD_ELASTIC_TIMEOUT, "600"))
    while time.time() < deadline:
        try:
            world_raw = client.get("rendezvous", "world")
            world = json.loads(world_raw) if world_raw else {"version": 0}
            if world.get("version", 0) > last_version:
                raw = client.get("rendezvous",
                                 f"slot/{hostname}/{local_rank}")
                if raw:
                    rec = json.loads(raw)
                    if rec.get("version", 0) == world["version"]:
                        os.environ[_config.HOROVOD_RANK] = str(rec["rank"])
                        os.environ[_config.HOROVOD_SIZE] = str(rec["size"])
                        os.environ[_config.HOROVOD_LOCAL_RANK] = \
                            str(rec["local_rank"])
                        os.environ[_config.HOROVOD_LOCAL_SIZE] = \
                            str(rec["local_size"])
                        os.environ[_config.HOROVOD_CROSS_RANK] = \
                            str(rec["cross_rank"])
                        os.environ[_config.HOROVOD_CROSS_SIZE] = \
                            str(rec["cross_size"])
                        os.environ["HVD_TPU_WORLD_VERSION"] = \
                            str(rec["version"])
                        return
        except Exception as e:
            get_logger().debug("rendezvous refresh retry: %s", e)
        time.sleep(0.5)
    raise HorovodInternalError(
        "timed out waiting for a slot assignment after reset")


def _reset(refresh_world: bool = True) -> None:
    """Full reinit: shutdown the runtime, re-rendezvous, re-init
    (common/elastic.py run_fn 'reinit' = shutdown + re-rendezvous).

    ``refresh_world=False`` for recovery from a collective failure with
    UNCHANGED membership (HorovodInternalError): every rank received the
    same error verdict and resets simultaneously into the same world, so
    there is no new world version to wait for — the slot env is still
    valid and only the JAX runtime needs rebuilding."""
    from .. import core as _core
    _core.shutdown()
    if os.environ.get("HOROVOD_ELASTIC") == "1":
        if refresh_world:
            _refresh_world_from_rendezvous()
            # New world: generation = (world_version, 0).  Newly spawned
            # workers get the same value from the driver (launch_support),
            # so every member of the new world scopes its negotiation keys
            # identically.
            os.environ["HVD_TPU_NEGOTIATION_GEN"] = \
                f"{os.environ.get('HVD_TPU_WORLD_VERSION', '0')}.0"
        else:
            # Same world, in-place recovery: every rank received the same
            # collective-failure verdict and resets together — bump the
            # same-world counter so the fresh negotiators never consume the
            # previous incarnation's KV records.
            cur = os.environ.get("HVD_TPU_NEGOTIATION_GEN", "0.0")
            w, _, c = cur.partition(".")
            os.environ["HVD_TPU_NEGOTIATION_GEN"] = \
                f"{w}.{int(c or 0) + 1}"
        import jax
        try:
            from jax._src import distributed as _jdist
            if getattr(_jdist.global_state, "client", None) is not None:
                jax.distributed.shutdown()
        except Exception as e:
            # A dead coordinator makes shutdown raise; the clear below still
            # severs this process from the stale runtime.
            get_logger().warning("jax.distributed shutdown failed: %s", e)
        try:
            # A world-size change needs a fresh multi-process runtime: the
            # backend was initialized for the OLD world, and
            # jax.distributed.initialize refuses to run on a live backend.
            # Dropping the backends forces re-initialization (and re-traces
            # every compiled step — the recompilation cost SURVEY.md §7
            # flags as inherent to elastic world changes).  Failure here
            # must be FATAL: continuing would silently reuse the old world's
            # runtime against the new world's env and hang collectives.
            from jax._src import api as _jax_api
            _jax_api.clear_backends()
        except Exception as e:
            raise HorovodInternalError(
                f"failed to reset the JAX backend for the new world: {e}"
            ) from e
    _core.init()


def run(func):
    """Elastic retry decorator (hvd.elastic.run, common/elastic.py:151).

    Usage::

        state = hvd.elastic.TpuState(params=params, opt_state=opt_state,
                                     epoch=0)

        @hvd.elastic.run
        def train(state):
            for epoch in range(state.epoch, 90):
                ...train...
                state.epoch = epoch
                state.commit()

        train(state)
    """
    @functools.wraps(func)
    def wrapper(state: State, *args, **kwargs):
        notification_manager.init()
        notification_manager.register_listener(state)
        skip_sync = False
        reset_required = False
        refresh_world = True
        try:
            while True:
                if reset_required:
                    _reset(refresh_world=refresh_world)
                    # Restore AFTER the backend reset: the in-memory commit
                    # holds host (numpy) copies, so restore re-materializes
                    # arrays on the NEW backend.  (Restoring before the
                    # reset would leave State attributes pointing at deleted
                    # buffers of the old backend.)  On the interrupt path
                    # this equals the current values: commit() saved
                    # immediately before raising.
                    state.restore()
                    state.on_reset()
                try:
                    if not skip_sync:
                        state.sync()
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    get_logger().info(
                        "elastic: collective failure — restoring last commit")
                    skip_sync = False
                    refresh_world = False  # membership unchanged
                except HostsUpdatedInterrupt as e:
                    get_logger().info(
                        "elastic: host membership changed — reinitializing")
                    skip_sync = e.skip_sync
                    refresh_world = True
                reset_required = True
        finally:
            notification_manager.remove_listener(state)

    return wrapper
