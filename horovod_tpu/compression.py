"""Gradient compression applied around allreduce.

Reference: horovod/torch/compression.py:20-74 (same classes duplicated per
framework) — ``Compression.none`` and ``Compression.fp16``, where fp16
compresses to half precision on the wire and decompresses back.

On TPU the natural wire dtype is **bfloat16** (same 8-bit exponent as f32 — no
range loss, which is why TPU hardware prefers it), so this build adds
``Compression.bf16`` and makes ``fp16`` keep its reference meaning.  Inside a
jit-compiled step the cast fuses into the psum's input/output, so compression
halves ICI bytes at zero extra kernel cost.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Compressor:
    """Interface (torch/compression.py:20)."""

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Default: no-op (torch/compression.py:34)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast floating tensors to fp16 for the wire (torch/compression.py:46)."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and \
                tensor.dtype != jnp.float16:
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native wire compression: bfloat16 keeps the f32 exponent."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating) and \
                tensor.dtype != jnp.bfloat16:
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            tensor = tensor.astype(ctx)
        return tensor


class Compression:
    """Option enum holder (torch/compression.py:70-74)."""
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
