"""TPU slice topology discovery and rank bookkeeping.

The reference derives rank/local_rank/cross_rank from MPI communicators split by
shared memory (mpi_controller.cc:75-81) or from launcher-injected env vars
(runner/gloo_run.py:66-78: ``HOROVOD_RANK``, ``HOROVOD_SIZE``,
``HOROVOD_LOCAL_RANK``, ``HOROVOD_CROSS_RANK``...).  On TPU the equivalent
information comes from (a) the launcher env, (b) an already-initialized
``jax.distributed`` runtime (process index/count + local vs. global devices),
or (c) a single-process fallback.

Two levels of identity coexist (see SURVEY.md §2.3 TPU mapping):

* **process level** — ``rank``/``size``/``local_*``/``cross_*`` exactly as the
  reference reports them; this is what user scripts branch on ("rank 0 writes
  checkpoints").
* **slot (chip) level** — the data plane is a ``jax.sharding.Mesh`` over every
  chip in the job; ``num_slots`` is its size.  Gradient averaging divides by
  ``num_slots``, matching the reference where one process drives one GPU so the
  two notions collapse.

Emulation: with ``HVD_TPU_EMULATE_RANKS=N`` (tests, CPU) a single process
presents N local devices as N ranks, which is how the hermetic test suite
exercises multi-rank numerics — the analog of the reference running its
parallel suite under ``horovodrun -np 2`` on CPU Gloo (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import List, Optional

from . import config as _config


@dataclasses.dataclass
class Topology:
    # Process-level identity (reference: horovod_rank/size/... C API,
    # operations.cc:934-1050).
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    # Slot (chip) level: the device mesh over which XLA collectives run.
    num_slots: int
    local_slots: int
    # Devices backing the mesh (jax devices, process-local list for this proc).
    devices: list = dataclasses.field(default_factory=list, repr=False)
    local_devices: list = dataclasses.field(default_factory=list, repr=False)
    emulated: bool = False
    hostname: str = ""
    # Per-node slot counts when derivable from the device list (multi-
    # controller: devices carry process_index); empty = assume homogeneous.
    slots_per_node: List[int] = dataclasses.field(default_factory=list)

    @property
    def is_homogeneous(self) -> bool:
        """True when every node has the same number of slots
        (reference: controller.h is_homogeneous_, computed by comparing
        local sizes across nodes in mpi_controller.cc:75-81)."""
        if self.slots_per_node:
            return len(set(self.slots_per_node)) <= 1
        return True


def _from_launcher_env() -> Optional[Topology]:
    """Topology from launcher-injected env (runner/gloo_run.py:66-78 analog)."""
    rank = os.environ.get(_config.HOROVOD_RANK)
    size = os.environ.get(_config.HOROVOD_SIZE)
    if rank is None or size is None:
        return None
    rank, size = int(rank), int(size)
    local_rank = int(os.environ.get(_config.HOROVOD_LOCAL_RANK, 0))
    local_size = int(os.environ.get(_config.HOROVOD_LOCAL_SIZE, 1))
    cross_rank = int(os.environ.get(_config.HOROVOD_CROSS_RANK, rank))
    cross_size = int(os.environ.get(_config.HOROVOD_CROSS_SIZE, size))
    return Topology(
        rank=rank, size=size,
        local_rank=local_rank, local_size=local_size,
        cross_rank=cross_rank, cross_size=cross_size,
        num_slots=size, local_slots=1,
        hostname=os.environ.get(_config.HOROVOD_HOSTNAME, socket.gethostname()),
    )


def detect(cfg: _config.Config) -> Topology:
    """Resolve process + slot topology.

    Resolution order: launcher env > jax.distributed multi-process > single
    process (with optional rank emulation over local devices).
    """
    import jax

    topo = _from_launcher_env()
    local_devices = list(jax.local_devices())
    all_devices = list(jax.devices())

    if topo is not None:
        topo.devices = all_devices
        topo.local_devices = local_devices
        topo.num_slots = max(topo.size, len(all_devices))
        topo.local_slots = len(local_devices)
        return topo

    n_proc = jax.process_count()
    if n_proc > 1:
        # Multi-controller: one process per host is the TPU norm; local/cross
        # follow the reference's shared-memory split semantics where "local"
        # means co-resident on a node (mpi_controller.cc:75-81).
        rank = jax.process_index()
        counts = {}
        for d in all_devices:
            counts[d.process_index] = counts.get(d.process_index, 0) + 1
        return Topology(
            rank=rank, size=n_proc,
            local_rank=0, local_size=1,
            cross_rank=rank, cross_size=n_proc,
            num_slots=len(all_devices), local_slots=len(local_devices),
            devices=all_devices, local_devices=local_devices,
            hostname=socket.gethostname(),
            slots_per_node=[counts[p] for p in sorted(counts)],
        )

    # Single process. Optionally emulate N ranks over N local devices.
    emulate = cfg.emulate_ranks
    if emulate:
        if emulate > len(local_devices):
            raise ValueError(
                f"HVD_TPU_EMULATE_RANKS={emulate} exceeds the "
                f"{len(local_devices)} available local devices")
        devices = local_devices[:emulate]
        return Topology(
            rank=0, size=emulate,
            local_rank=0, local_size=emulate,
            cross_rank=0, cross_size=1,
            num_slots=emulate, local_slots=emulate,
            devices=devices, local_devices=devices,
            emulated=True, hostname=socket.gethostname(),
        )

    return Topology(
        rank=0, size=1,
        local_rank=0, local_size=1,
        cross_rank=0, cross_size=1,
        num_slots=len(all_devices), local_slots=len(local_devices),
        devices=all_devices, local_devices=local_devices,
        hostname=socket.gethostname(),
    )
