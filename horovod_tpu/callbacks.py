"""Training callbacks — the Keras callback surface, framework-neutral.

Reference: horovod/_keras/callbacks.py — BroadcastGlobalVariablesCallback
(rank 0's initial variables to all), MetricAverageCallback (allreduce-average
epoch metrics), LearningRateScheduleCallback / LearningRateWarmupCallback
(scale + warm up the LR with world size, the "facebook 1-hour" recipe).

The TPU build has no Keras dependency; these are plain objects with
``on_train_begin`` / ``on_epoch_begin`` / ``on_epoch_end`` hooks driven by
any training loop (see examples/), and an adapter is trivial for users who
run Keras-style loops.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp

from . import core as _core
from . import ops as _ops
from . import functions as _functions


class Callback:
    def on_train_begin(self, state=None):
        pass

    def on_epoch_begin(self, epoch: int, state=None):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        pass

    def on_batch_begin(self, batch: int, state=None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial parameters from root at train begin
    (_keras/callbacks.py BroadcastGlobalVariablesCallbackImpl).  ``state``
    must expose ``params`` (and optionally ``opt_state``)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state=None):
        if state is None:
            return
        if hasattr(state, "params"):
            state.params = _functions.broadcast_variables(
                state.params, root_rank=self.root_rank)
        if hasattr(state, "opt_state"):
            state.opt_state = _functions.broadcast_optimizer_state(
                state.opt_state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average metrics over ranks at epoch end
    (_keras/callbacks.py MetricAverageCallbackImpl)."""

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        if not logs:
            return
        for k, val in list(logs.items()):
            arr = jnp.asarray(val, jnp.float32)
            avg = _ops.allreduce(arr, op=_ops.ReduceOp.AVERAGE)
            logs[k] = float(jnp.ravel(jnp.asarray(avg))[0])


class LearningRateScheduleCallback(Callback):
    """Multiply the LR by ``multiplier`` within [start_epoch, end_epoch)
    (_keras/callbacks.py LearningRateScheduleCallbackImpl).  ``set_lr`` is a
    callable the training loop provides (optax users typically close over a
    mutable schedule scale)."""

    def __init__(self, set_lr: Callable[[float], None], initial_lr: float,
                 multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.set_lr = set_lr
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self.multiplier_fn = multiplier
        else:
            self.multiplier_fn = lambda epoch: multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int, state=None):
        if self._in_range(epoch):
            self.set_lr(self.initial_lr * self.multiplier_fn(epoch))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warm-up from lr to lr*size over ``warmup_epochs``
    (_keras/callbacks.py LearningRateWarmupCallbackImpl — the linear-scaling
    + warm-up recipe).  After warm-up the multiplier is world size."""

    def __init__(self, set_lr: Callable[[float], None], initial_lr: float,
                 warmup_epochs: int = 5, momentum_correction: bool = True,
                 verbose: bool = False):
        self.warmup_epochs = warmup_epochs
        self.momentum_correction = momentum_correction
        if momentum_correction:
            import warnings
            warnings.warn(
                "momentum_correction is accepted for API parity but not "
                "applied automatically: with optax, wrap your optimizer in "
                "optax.inject_hyperparams and rescale momentum alongside "
                "set_lr", stacklevel=2)

        def multiplier(epoch):
            size = _core.num_slots()
            if epoch >= warmup_epochs:
                return float(size)
            # epoch 0 -> exactly 1.0 (true warm start), reaching `size` at
            # epoch == warmup_epochs (linear, the 1-hour-ImageNet recipe).
            return 1.0 + (size - 1.0) * epoch / max(warmup_epochs, 1)

        super().__init__(set_lr, initial_lr, multiplier,
                         start_epoch=0, end_epoch=None)


class EarlyStoppingCallback(Callback):
    """Stop training when a monitored metric stops improving (the Keras
    EarlyStopping the reference's estimators accept as a fit callback).

    SPMD contract: the decision must be IDENTICAL on every rank — monitor
    only metrics that are already rank-consistent (the estimator's
    ``loss``/``val_loss`` are metric-averaged over ranks before callbacks
    fire; hand-rolled loops should apply MetricAverageCallback first).
    The driving loop checks ``stop_training`` after ``on_epoch_end``."""

    def __init__(self, monitor: str = "val_loss", patience: int = 0,
                 min_delta: float = 0.0, mode: str = "min"):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.mode = mode
        self.best: Optional[float] = None
        self.wait = 0
        self.stop_training = False
        self.stopped_epoch: Optional[int] = None

    def _improved(self, value: float) -> bool:
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        if not logs or self.monitor not in logs:
            # Keras parity: warn, don't silently disable — the default
            # monitor 'val_loss' is absent when no validation is
            # configured, and a typoed name would otherwise train every
            # epoch with the user none the wiser.
            if not getattr(self, "_warned_missing", False):
                self._warned_missing = True
                from .utils import get_logger
                get_logger().warning(
                    "EarlyStoppingCallback: monitored metric %r not in "
                    "epoch logs (keys: %s) — early stopping inactive",
                    self.monitor, sorted(logs or {}))
            return
        value = float(logs[self.monitor])
        if self._improved(value):
            self.best = value
            self.wait = 0
            return
        self.wait += 1
        # Keras semantics: stop once `patience` epochs pass with no
        # improvement (wait >= patience; patience=0 stops on the first).
        if self.wait >= max(self.patience, 1):
            self.stop_training = True
            self.stopped_epoch = epoch


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    @property
    def stop_training(self) -> bool:
        return any(getattr(cb, "stop_training", False)
                   for cb in self.callbacks)

    def __getattr__(self, hook):
        if not hook.startswith("on_"):
            raise AttributeError(hook)

        def fire(*args, **kwargs):
            for cb in self.callbacks:
                getattr(cb, hook)(*args, **kwargs)

        return fire
