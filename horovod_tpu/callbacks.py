"""Training callbacks — the Keras callback surface, framework-neutral.

Reference: horovod/_keras/callbacks.py — BroadcastGlobalVariablesCallback
(rank 0's initial variables to all), MetricAverageCallback (allreduce-average
epoch metrics), LearningRateScheduleCallback / LearningRateWarmupCallback
(scale + warm up the LR with world size, the "facebook 1-hour" recipe).

The TPU build has no Keras dependency; these are plain objects with
``on_train_begin`` / ``on_epoch_begin`` / ``on_epoch_end`` hooks driven by
any training loop (see examples/), and an adapter is trivial for users who
run Keras-style loops.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp

from . import core as _core
from . import ops as _ops
from . import functions as _functions


class Callback:
    def on_train_begin(self, state=None):
        pass

    def on_epoch_begin(self, epoch: int, state=None):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        pass

    def on_batch_begin(self, batch: int, state=None):
        pass


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial parameters from root at train begin
    (_keras/callbacks.py BroadcastGlobalVariablesCallbackImpl).  ``state``
    must expose ``params`` (and optionally ``opt_state``)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state=None):
        if state is None:
            return
        if hasattr(state, "params"):
            state.params = _functions.broadcast_variables(
                state.params, root_rank=self.root_rank)
        if hasattr(state, "opt_state"):
            state.opt_state = _functions.broadcast_optimizer_state(
                state.opt_state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average metrics over ranks at epoch end
    (_keras/callbacks.py MetricAverageCallbackImpl)."""

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None,
                     state=None):
        if not logs:
            return
        for k, val in list(logs.items()):
            arr = jnp.asarray(val, jnp.float32)
            avg = _ops.allreduce(arr, op=_ops.ReduceOp.AVERAGE)
            logs[k] = float(jnp.ravel(jnp.asarray(avg))[0])


class LearningRateScheduleCallback(Callback):
    """Multiply the LR by ``multiplier`` within [start_epoch, end_epoch)
    (_keras/callbacks.py LearningRateScheduleCallbackImpl).  ``set_lr`` is a
    callable the training loop provides (optax users typically close over a
    mutable schedule scale)."""

    def __init__(self, set_lr: Callable[[float], None], initial_lr: float,
                 multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True):
        self.set_lr = set_lr
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self.multiplier_fn = multiplier
        else:
            self.multiplier_fn = lambda epoch: multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int, state=None):
        if self._in_range(epoch):
            self.set_lr(self.initial_lr * self.multiplier_fn(epoch))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Linear warm-up from lr to lr*size over ``warmup_epochs``
    (_keras/callbacks.py LearningRateWarmupCallbackImpl — the linear-scaling
    + warm-up recipe).  After warm-up the multiplier is world size."""

    def __init__(self, set_lr: Callable[[float], None], initial_lr: float,
                 warmup_epochs: int = 5, momentum_correction: bool = True,
                 verbose: bool = False):
        self.warmup_epochs = warmup_epochs
        self.momentum_correction = momentum_correction
        if momentum_correction:
            import warnings
            warnings.warn(
                "momentum_correction is accepted for API parity but not "
                "applied automatically: with optax, wrap your optimizer in "
                "optax.inject_hyperparams and rescale momentum alongside "
                "set_lr", stacklevel=2)

        def multiplier(epoch):
            size = _core.num_slots()
            if epoch >= warmup_epochs:
                return float(size)
            # epoch 0 -> exactly 1.0 (true warm start), reaching `size` at
            # epoch == warmup_epochs (linear, the 1-hour-ImageNet recipe).
            return 1.0 + (size - 1.0) * epoch / max(warmup_epochs, 1)

        super().__init__(set_lr, initial_lr, multiplier,
                         start_epoch=0, end_epoch=None)


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def __getattr__(self, hook):
        if not hook.startswith("on_"):
            raise AttributeError(hook)

        def fire(*args, **kwargs):
            for cb in self.callbacks:
                getattr(cb, hook)(*args, **kwargs)

        return fire
