"""Gaussian-process Bayesian optimization for the autotuner.

Reference: horovod/common/optim/bayesian_optimization.cc (194 LoC) +
gaussian_process.cc (183 LoC) — the ParameterManager's search engine: fit a
GP (RBF kernel) to (knob, score) samples, maximize expected improvement to
pick the next knob (parameter_manager.h:42-110).

NumPy implementation: RBF kernel with jitter, Cholesky posterior, EI
maximized over a dense candidate grid (the reference uses l-bfgs over the
same acquisition; a grid is equivalent for 1-2 dimensional knob spaces).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np


class GaussianProcess:
    """GP regression with an RBF kernel (gaussian_process.cc analog)."""

    def __init__(self, length_scale: float = 1.0, signal_var: float = 1.0,
                 noise: float = 1e-4):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise = noise
        self._x: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        """x: [n, d] normalized inputs; y: [n] scores (standardized
        internally)."""
        self._x = np.asarray(x, float)
        y = np.asarray(y, float)
        self._y_mean = float(y.mean()) if len(y) else 0.0
        self._y_std = float(y.std()) or 1.0
        self._y = (y - self._y_mean) / self._y_std
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, self._y))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and std at x [m, d] (de-standardized)."""
        x = np.asarray(x, float)
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(self.signal_var - (v ** 2).sum(0), 1e-12, None)
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def _norm_cdf(z):
    from math import erf
    return 0.5 * (1.0 + np.vectorize(erf)(z / math.sqrt(2)))


def expected_improvement(mean: np.ndarray, std: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    """EI acquisition (bayesian_optimization.cc ExpectedImprovement)."""
    imp = mean - best - xi
    z = imp / np.where(std > 0, std, 1.0)
    ei = imp * _norm_cdf(z) + std * _norm_pdf(z)
    return np.where(std > 0, ei, 0.0)


class BayesianOptimizer:
    """Sequential maximizer over a bounded 1-D knob
    (bayesian_optimization.cc BayesianOptimization)."""

    def __init__(self, low: float, high: float, grid: int = 256):
        self.low, self.high = float(low), float(high)
        self._xs: List[float] = []
        self._ys: List[float] = []
        self._grid = np.linspace(0.0, 1.0, grid)

    def _norm(self, x: float) -> float:
        return (x - self.low) / (self.high - self.low)

    def _denorm(self, u: float) -> float:
        return self.low + u * (self.high - self.low)

    def observe(self, x: float, y: float) -> None:
        self._xs.append(self._norm(x))
        self._ys.append(y)

    def suggest(self) -> float:
        """Next knob value: a fixed space-filling start (0.5, 0.1, 0.9),
        then argmax-EI.  Fully deterministic given the observation history —
        the schedule must be replayable (rank 0 publishes it)."""
        if len(self._xs) < 3:
            # deterministic space-filling start: 0.5, 0.1, 0.9
            return self._denorm([0.5, 0.1, 0.9][len(self._xs)])
        gp = GaussianProcess(length_scale=0.2)
        gp.fit(np.asarray(self._xs)[:, None], np.asarray(self._ys))
        mean, std = gp.predict(self._grid[:, None])
        ei = expected_improvement(mean, std, best=max(self._ys))
        return self._denorm(float(self._grid[int(np.argmax(ei))]))

    def best(self) -> float:
        if not self._xs:
            return self._denorm(0.5)
        return self._denorm(self._xs[int(np.argmax(self._ys))])
