"""Subprocess execution with process-group cleanup and output forwarding.

Reference: horovod/runner/common/util/safe_shell_exec.py — fork/exec with a
process group so the whole worker tree dies together, stdout/err forwarding
threads with per-rank prefixes, and event-triggered termination.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5


def _forward_stream(stream, out, prefix: str, prefix_timestamp: bool):
    """Line-forward a worker stream with "[rank]<tag>" prefixes
    (gloo_run.py:116-201 output forwarding)."""
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        if prefix:
            ts = time.strftime("%Y-%m-%d %H:%M:%S: ") if prefix_timestamp \
                else ""
            line = f"{prefix}{ts}{line}"
        out.write(line)
        out.flush()
    stream.close()


def execute(command, env: Optional[Dict[str, str]] = None,
            stdout=None, stderr=None, prefix: str = "",
            prefix_timestamp: bool = False,
            events: Optional[List[threading.Event]] = None) -> int:
    """Run command in its own process group; on event or interrupt, terminate
    the whole group (safe_shell_exec.py semantics)."""
    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    proc = subprocess.Popen(
        command, env=env, shell=isinstance(command, str),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        preexec_fn=os.setsid)

    threads = [
        threading.Thread(target=_forward_stream,
                         args=(proc.stdout, stdout, prefix, prefix_timestamp),
                         daemon=True),
        threading.Thread(target=_forward_stream,
                         args=(proc.stderr, stderr, prefix, prefix_timestamp),
                         daemon=True),
    ]
    for t in threads:
        t.start()

    stop_watcher = threading.Event()

    def watch_events():
        while not stop_watcher.is_set():
            if events and any(e.is_set() for e in events):
                terminate(proc)
                return
            time.sleep(0.1)

    watcher = None
    if events:
        watcher = threading.Thread(target=watch_events, daemon=True)
        watcher.start()

    try:
        ret = proc.wait()
    except KeyboardInterrupt:
        terminate(proc)
        ret = proc.wait()
    finally:
        stop_watcher.set()
    for t in threads:
        t.join(timeout=1)
    if watcher:
        watcher.join(timeout=1)
    return ret


def terminate(proc: subprocess.Popen) -> None:
    """SIGTERM the process group, escalate to SIGKILL after the grace
    period."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.time() + GRACEFUL_TERMINATION_TIME_S
    while time.time() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.1)
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
