"""``horovodrun`` — the launcher CLI.

Reference: horovod/runner/launch.py:286 (parse_args: every knob as a flag
writing ``HOROVOD_*`` env), :594 (_run_static), :689 (_run_elastic), :747
(run_controller choosing gloo/mpi/jsrun), plus the YAML ``--config-file``
layer (runner/common/util/config_parser.py).

TPU build: one launch path — spawn one worker process per slot with
rendezvous env injected (gloo_run.py:66-78 analog), local slots via
subprocess, remote hosts via ssh.  The legacy backend selectors
(--gloo/--mpi) are accepted for compatibility and ignored: there is exactly
one backend (XLA collectives).  ``jax.distributed`` coordinator bootstrap
replaces MPI_Init (core.py _maybe_join_distributed).
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import threading
from typing import Dict, List

from .. import config as _config
from ..version import __version__
from . import hosts as _hosts
from . import safe_shell_exec
from .http_server import RendezvousServer


def make_override_action(override_args):
    """argparse action that records explicitly-set flags
    (launch.py:158 make_override_action)."""
    class StoreOverrideAction(argparse.Action):
        def __call__(self, parser, namespace, values, option_string=None):
            override_args.add(self.dest)
            setattr(namespace, self.dest, values)

    class StoreTrueOverrideAction(argparse.Action):
        def __init__(self, option_strings, dest, nargs=0, **kwargs):
            super().__init__(option_strings, dest, nargs=0, **kwargs)

        def __call__(self, parser, namespace, values, option_string=None):
            override_args.add(self.dest)
            setattr(namespace, self.dest, True)

    return StoreOverrideAction, StoreTrueOverrideAction


def check_build() -> str:
    """The ``--check-build`` matrix (reference runner/launch.py:110
    check_build), answered from the core's built/enabled surface
    (core.py:365-417): one framework (JAX) and one tensor-op backend (XLA
    collectives) are the design — the legacy rows print unchecked, in the
    reference's own format, so capability-probing scripts read the truth."""
    from .. import core

    def c(v):
        return "X" if v else " "

    return f"""\
Horovod-TPU v{__version__}:

Available Frameworks:
    [X] JAX
    [ ] TensorFlow
    [ ] PyTorch
    [ ] MXNet

Available Controllers:
    [{c(core.xla_enabled())}] XLA (KV rendezvous + jax.distributed)
    [{c(core.mpi_enabled())}] MPI
    [{c(core.gloo_enabled())}] Gloo

Available Tensor Operations:
    [{c(core.xla_built())}] XLA collectives (ICI/DCN)
    [{c(core.nccl_built())}] NCCL
    [{c(core.ddl_built())}] DDL
    [{c(core.ccl_built())}] CCL
    [{c(core.mpi_built())}] MPI
    [{c(core.gloo_built())}] Gloo"""


def parse_args(argv=None):
    """Flag surface mirroring runner/launch.py:286-578."""
    override_args = set()
    Store, StoreTrue = make_override_action(override_args)

    parser = argparse.ArgumentParser(
        prog="horovodrun",
        description="Horovod-compatible launcher for the TPU-native runtime.")
    parser.add_argument("-v", "--version", action="version",
                        version=__version__)
    parser.add_argument("-cb", "--check-build", action="store_true",
                        dest="check_build",
                        help="Print the framework/controller/tensor-op "
                             "build matrix and exit.")
    parser.add_argument("-np", "--num-proc", dest="np", type=int,
                        help="Total number of training processes.")
    parser.add_argument("-p", "--ssh-port", dest="ssh_port", type=int,
                        help="SSH port on all hosts.")
    parser.add_argument("-i", "--ssh-identity-file", dest="ssh_identity_file",
                        help="SSH identity (private key) file.")
    parser.add_argument("--network-interface", dest="nics",
                        help="Comma-separated network interfaces to use.")
    parser.add_argument("--output-filename", dest="output_filename",
                        help="Per-rank output redirection directory.")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML config file (launch.py --config-file).")
    parser.add_argument("--disable-cache", action=StoreTrue,
                        dest="disable_cache",
                        help="Disable the response cache.")
    parser.add_argument("--start-timeout", dest="start_timeout", type=int,
                        default=600)

    group_host = parser.add_argument_group("host arguments")
    group_host.add_argument("-H", "--hosts", dest="hosts",
                            help='Host list, e.g. "h1:4,h2:4".')
    group_host.add_argument("-hostfile", "--hostfile", dest="hostfile",
                            help='Hostfile with "hostname slots=N" lines.')

    parser.add_argument("--binding-args", dest="binding_args",
                        help="jsrun binding arguments (replaces the "
                             "generated --erf_input rankfile; reference "
                             "launch.py --binding-args).")

    group_controller = parser.add_mutually_exclusive_group()
    group_controller.add_argument("--gloo", "--use-gloo", dest="use_gloo",
                                  action="store_true",
                                  help="Compatibility no-op (single backend).")
    group_controller.add_argument("--mpi", "--use-mpi", dest="use_mpi",
                                  action="store_true",
                                  help="Compatibility no-op (single backend).")
    group_controller.add_argument("--jsrun", "--use-jsrun",
                                  dest="use_jsrun",
                                  action="store_true",
                                  help="LSF/jsrun launch (unsupported; "
                                       "errors with a migration pointer).")

    group_params = parser.add_argument_group("tuneable parameter arguments")
    group_params.add_argument("--fusion-threshold-mb", action=Store,
                              type=int, dest="fusion_threshold_mb",
                              help="Fusion buffer threshold in MB.")
    group_params.add_argument("--cycle-time-ms", action=Store, type=float,
                              dest="cycle_time_ms")
    group_params.add_argument("--cache-capacity", action=Store, type=int,
                              dest="cache_capacity")
    group_params.add_argument("--hierarchical-allreduce", action=StoreTrue,
                              dest="hierarchical_allreduce")
    group_params.add_argument("--hierarchical-allgather", action=StoreTrue,
                              dest="hierarchical_allgather")

    group_autotune = parser.add_argument_group("autotune arguments")
    group_autotune.add_argument("--autotune", action=StoreTrue,
                                dest="autotune")
    group_autotune.add_argument("--autotune-log-file", action=Store,
                                dest="autotune_log_file")

    group_timeline = parser.add_argument_group("timeline arguments")
    group_timeline.add_argument("--timeline-filename", action=Store,
                                dest="timeline_filename")
    group_timeline.add_argument("--timeline-mark-cycles", action=StoreTrue,
                                dest="timeline_mark_cycles")

    group_stall = parser.add_argument_group("stall check arguments")
    group_stall.add_argument("--no-stall-check", action=StoreTrue,
                             dest="no_stall_check")
    group_stall.add_argument("--stall-check-warning-time-seconds",
                             action=Store, type=int,
                             dest="stall_check_warning_time_seconds")
    group_stall.add_argument("--stall-check-shutdown-time-seconds",
                             action=Store, type=int,
                             dest="stall_check_shutdown_time_seconds")

    group_library = parser.add_argument_group("library arguments")
    group_library.add_argument("--mpi-threads-disable", action=StoreTrue,
                               dest="mpi_threads_disable",
                               help="Compatibility no-op.")
    group_library.add_argument("--num-nccl-streams", action=Store, type=int,
                               dest="num_nccl_streams",
                               help="Compatibility no-op.")
    group_library.add_argument("--thread-affinity", action=Store, type=int,
                               dest="thread_affinity")

    group_logging = parser.add_argument_group("logging arguments")
    group_logging.add_argument("--log-level", action=Store,
                               dest="log_level",
                               choices=["TRACE", "DEBUG", "INFO", "WARNING",
                                        "ERROR", "FATAL"])
    group_logging.add_argument("--log-with-timestamp", action=StoreTrue,
                               dest="log_with_timestamp")
    group_logging.add_argument("--log-hide-timestamp", action=StoreTrue,
                               dest="log_hide_timestamp")
    group_logging.add_argument("--prefix-output-with-timestamp",
                               action="store_true",
                               dest="prefix_output_with_timestamp")

    group_elastic = parser.add_argument_group("elastic arguments")
    group_elastic.add_argument("--min-np", "--min-num-proc", type=int,
                               dest="min_np")
    group_elastic.add_argument("--max-np", "--max-num-proc", type=int,
                               dest="max_np")
    group_elastic.add_argument("--slots", type=int, dest="slots",
                               help="Slots per host for elastic discovery.")
    group_elastic.add_argument("--host-discovery-script",
                               dest="host_discovery_script")
    group_elastic.add_argument("--reset-limit", type=int, dest="reset_limit")
    group_elastic.add_argument("--blacklist-cooldown-range", type=int,
                               nargs=2, dest="blacklist_cooldown_range")

    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Command to run on each rank.")

    args = parser.parse_args(argv)
    args.override_args = override_args
    if args.check_build:
        print(check_build())
        raise SystemExit(0)
    # Honest no-op/unsupported handling (reference launch.py:747
    # run_controller chooses gloo/mpi/jsrun; here there is exactly one
    # backend).  Silent acceptance would let an --mpi user assume mpirun
    # semantics they are not getting.
    if args.use_jsrun:
        # jsrun as the SPAWN TRANSPORT (reference launch.py:760
        # run_controller -> js_run): one jsrun covers every rank; each
        # task runs the jsrun_shim, which maps its JSM rank onto the
        # rendezvous slot contract.  The collective backend is still XLA
        # — there is no MPI controller to select (docs/migration.md).
        from . import lsf
        if not lsf.using_lsf():
            parser.error(
                "--jsrun requires an LSF allocation (LSB_JOBID is not "
                "set). Outside LSF, launch with -H/--hostfile over "
                "ssh/loopback instead — see docs/migration.md "
                "(launchers table).")
        if not lsf.is_jsrun_installed():
            parser.error(
                "--jsrun: the jsrun executable is not on PATH in this "
                "LSF allocation.")
        if args.min_np is not None or args.max_np is not None or \
                args.host_discovery_script is not None:
            # The elastic driver respawns workers per reshape over
            # ssh/loopback; jsrun has no per-worker respawn.  Error
            # loudly rather than silently ignoring --jsrun (the ssh
            # fallback would hang on jsrun-only clusters).
            parser.error(
                "--jsrun cannot be combined with elastic flags "
                "(--min-np/--max-np/--host-discovery-script): elastic "
                "worlds respawn workers over ssh/loopback. Run elastic "
                "without --jsrun, or run --jsrun static.")
    if args.use_mpi or args.use_gloo:
        flag = "--mpi" if args.use_mpi else "--gloo"
        print(f"horovodrun: note: {flag} is accepted for compatibility and "
              "ignored — workers always launch over ssh/loopback with the "
              "single XLA collective backend (see docs/migration.md).",
              file=sys.stderr)
    if args.config_file:
        _apply_config_file(args)
    return args


def _apply_config_file(args):
    """YAML config → args, CLI flags win (config_parser.py precedence)."""
    import yaml
    with open(args.config_file) as f:
        cfg = yaml.safe_load(f) or {}
    mapping = {
        "fusion_threshold_mb": "fusion-threshold-mb",
        "cycle_time_ms": "cycle-time-ms",
        "cache_capacity": "cache-capacity",
        "hierarchical_allreduce": "hierarchical-allreduce",
        "hierarchical_allgather": "hierarchical-allgather",
        "autotune": "autotune",
        "autotune_log_file": "autotune-log-file",
        "timeline_filename": "timeline-filename",
        "timeline_mark_cycles": "timeline-mark-cycles",
        "no_stall_check": "no-stall-check",
        "stall_check_warning_time_seconds":
            "stall-check-warning-time-seconds",
        "stall_check_shutdown_time_seconds":
            "stall-check-shutdown-time-seconds",
        "log_level": "log-level",
    }
    flat = {}
    for section in cfg.values() if isinstance(cfg, dict) else []:
        if isinstance(section, dict):
            flat.update(section)
    if isinstance(cfg, dict):
        flat.update({k: v for k, v in cfg.items()
                     if not isinstance(v, dict)})
    for dest, yaml_key in mapping.items():
        if dest in args.override_args:
            continue  # CLI beats config file
        for k in (yaml_key, dest):
            if k in flat:
                setattr(args, dest, flat[k])
                break


def env_from_args(args) -> Dict[str, str]:
    """Flags → HOROVOD_* env (launch.py + config_parser.set_env_from_args)."""
    env = {}
    if getattr(args, "fusion_threshold_mb", None) is not None:
        env[_config.HOROVOD_FUSION_THRESHOLD] = str(
            args.fusion_threshold_mb * 1024 * 1024)
    if getattr(args, "cycle_time_ms", None) is not None:
        env[_config.HOROVOD_CYCLE_TIME] = str(args.cycle_time_ms)
    if getattr(args, "cache_capacity", None) is not None:
        env[_config.HOROVOD_CACHE_CAPACITY] = str(args.cache_capacity)
    if getattr(args, "disable_cache", None):
        env[_config.HOROVOD_CACHE_CAPACITY] = "0"
    if getattr(args, "hierarchical_allreduce", None):
        env[_config.HOROVOD_HIERARCHICAL_ALLREDUCE] = "1"
    if getattr(args, "hierarchical_allgather", None):
        env[_config.HOROVOD_HIERARCHICAL_ALLGATHER] = "1"
    if getattr(args, "autotune", None):
        env[_config.HOROVOD_AUTOTUNE] = "1"
    if getattr(args, "autotune_log_file", None):
        env[_config.HOROVOD_AUTOTUNE_LOG] = args.autotune_log_file
    if getattr(args, "timeline_filename", None):
        env[_config.HOROVOD_TIMELINE] = args.timeline_filename
    if getattr(args, "timeline_mark_cycles", None):
        env[_config.HOROVOD_TIMELINE_MARK_CYCLES] = "1"
    if getattr(args, "no_stall_check", None):
        env[_config.HOROVOD_STALL_CHECK_DISABLE] = "1"
    if getattr(args, "stall_check_warning_time_seconds", None) is not None:
        env[_config.HOROVOD_STALL_CHECK_TIME_SECONDS] = str(
            args.stall_check_warning_time_seconds)
    if getattr(args, "stall_check_shutdown_time_seconds", None) is not None:
        env[_config.HOROVOD_STALL_SHUTDOWN_TIME_SECONDS] = str(
            args.stall_check_shutdown_time_seconds)
    if getattr(args, "log_level", None):
        env[_config.HOROVOD_LOG_LEVEL] = args.log_level.lower()
    if getattr(args, "log_hide_timestamp", None):
        env[_config.HOROVOD_LOG_HIDE_TIME] = "1"
    return env


def _worker_env(base_env: Dict[str, str], slot: _hosts.SlotInfo,
                rendezvous_addr: str, rendezvous_port: int,
                coordinator: str) -> Dict[str, str]:
    """Per-slot rendezvous env (gloo_run.py:66-78)."""
    env = dict(base_env)
    env.update(slot.env())
    env.update({
        _config.HOROVOD_RENDEZVOUS_ADDR: rendezvous_addr,
        _config.HOROVOD_RENDEZVOUS_PORT: str(rendezvous_port),
        "HVD_TPU_COORDINATOR": coordinator,
    })
    return env


def _is_local(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname())


def _ssh_command(slot: _hosts.SlotInfo, command: List[str],
                 env: Dict[str, str], args) -> List[str]:
    """Remote launch line (gloo_run.py get_remote_command analog)."""
    import shlex
    exports = " ".join(f"{k}={shlex.quote(str(v))}" for k, v in env.items()
                       if k.startswith(("HOROVOD_", "HVD_TPU_", "PATH",
                                        "PYTHONPATH")))
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if args.ssh_port:
        ssh += ["-p", str(args.ssh_port)]
    if args.ssh_identity_file:
        ssh += ["-i", args.ssh_identity_file]
    remote = f"cd {shlex.quote(os.getcwd())} && env {exports} " + \
        " ".join(shlex.quote(c) for c in command)
    return ssh + [slot.hostname, remote]


def _run_static(args, on_rendezvous=None) -> int:
    """Static (fixed world) launch (launch.py:594 _run_static).

    ``on_rendezvous`` (internal): called with the live RendezvousServer
    after init — runner.run() captures its KV cache to collect per-rank
    results shipped back by workers (runner/__init__.py:95 contract)."""
    if args.hostfile:
        host_list = _hosts.parse_host_files(args.hostfile)
    elif args.hosts:
        host_list = _hosts.parse_hosts(args.hosts)
    else:
        from . import lsf
        if lsf.using_lsf() and (args.np is None or
                                getattr(args, "use_jsrun", False)):
            # Inside an LSF allocation the granted hosts ARE the world
            # (reference launch.py:295 makes -np optional under LSF).
            # An explicit -np WITHOUT --jsrun keeps the localhost
            # default — `horovodrun -np 1` in an interactive bsub
            # session must not ssh-fan-out across the allocation.
            try:
                host_list = lsf.lsf_hosts()
            except RuntimeError as e:
                raise SystemExit(f"horovodrun: {e}")
        else:
            np_ = args.np or 1
            host_list = [_hosts.HostInfo("localhost", np_)]
    np_ = args.np or sum(h.slots for h in host_list)
    assignments = _hosts.get_host_assignments(host_list, np_)

    rendezvous = RendezvousServer(verbose=args.verbose)
    port = rendezvous.start()
    rendezvous.init(assignments)
    if on_rendezvous is not None:
        on_rendezvous(rendezvous)
    has_remote = any(not _is_local(h.hostname) for h in host_list)
    addr = socket.gethostbyname(socket.gethostname()) if has_remote \
        else "127.0.0.1"
    if has_remote:
        # NIC selection (driver_service.py:122-194): explicit
        # --network-interface wins; otherwise probe every remote host and
        # pick a launcher address they can all actually reach.
        from . import nic_probe
        if args.nics:
            explicit = nic_probe.addr_for_interfaces(args.nics.split(","))
            if explicit:
                addr = explicit
        else:
            try:
                import shlex
                remote = sorted({h.hostname for h in host_list
                                 if not _is_local(h.hostname)})
                candidates = [addr] + [
                    a for addrs in
                    nic_probe.local_interfaces().values() for a in addrs
                    if a != addr]
                cand_arg = ",".join(f"{a}:{port}" for a in candidates)

                def spawn_probe(host):
                    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
                    if args.ssh_port:
                        cmd += ["-p", str(args.ssh_port)]
                    if args.ssh_identity_file:
                        cmd += ["-i", args.ssh_identity_file]
                    cmd += [host,
                            f"cd {shlex.quote(os.getcwd())} && "
                            f"{shlex.quote(sys.executable)} -m "
                            f"horovod_tpu.runner.nic_probe --candidates "
                            f"{cand_arg} --host {host}"]
                    safe_shell_exec.execute(cmd, env=dict(os.environ))

                _, routable = nic_probe.discover_common_address(
                    rendezvous, remote, spawn_probe, candidates, port,
                    timeout=float(os.environ.get(
                        "HVD_TPU_NIC_PROBE_TIMEOUT", "30")))
                if routable:
                    addr = routable
                else:
                    print(f"horovodrun: no probed launcher address was "
                          f"reachable from all hosts; falling back to "
                          f"{addr}", file=sys.stderr)
            except Exception as e:
                print(f"horovodrun: NIC probing failed ({e}); using "
                      f"{addr}", file=sys.stderr)
    # The jax.distributed coordinator runs inside rank 0's process.  With any
    # remote worker in the job, loopback would point remote workers at
    # themselves — use a routable name for rank 0's host instead.
    coord_host = assignments[0].hostname
    if _is_local(coord_host):
        coord_addr = addr  # routable self-address when remotes exist
    else:
        coord_addr = coord_host
    pick_coordinator_base_port(_is_local(coord_host))
    coordinator = f"{coord_addr}:{int(os.environ.get('HVD_TPU_COORD_PORT', 29400))}"

    base_env = {k: v for k, v in os.environ.items()}
    base_env.update(env_from_args(args))

    if getattr(args, "use_jsrun", False):
        try:
            return _jsrun_spawn(args, assignments, base_env, addr, port,
                                coordinator)
        finally:
            rendezvous.stop()

    threads = []
    rets = [None] * len(assignments)
    failure = threading.Event()

    def run_slot(i: int, slot: _hosts.SlotInfo):
        out_fh = err_fh = None
        try:
            env = _worker_env(base_env, slot, addr, port, coordinator)
            prefix = f"[{slot.rank}]<stdout>:" if len(assignments) > 1 else ""
            if _is_local(slot.hostname):
                cmd = args.command
            else:
                cmd = _ssh_command(slot, args.command, env, args)
            stdout = stderr = None
            if args.output_filename:
                # Per-rank output files (reference --output-filename: a
                # directory with rank.N/stdout|stderr).
                d = os.path.join(args.output_filename, f"rank.{slot.rank}")
                os.makedirs(d, exist_ok=True)
                out_fh = open(os.path.join(d, "stdout"), "w")
                err_fh = open(os.path.join(d, "stderr"), "w")
                stdout, stderr, prefix = out_fh, err_fh, ""
            rets[i] = safe_shell_exec.execute(
                cmd, env=env, prefix=prefix, stdout=stdout, stderr=stderr,
                prefix_timestamp=args.prefix_output_with_timestamp,
                events=[failure])
        except Exception as e:  # spawn failure must count as rank failure
            print(f"horovodrun: rank {slot.rank} failed to launch: {e}",
                  file=sys.stderr)
            rets[i] = 1
        finally:
            for fh in (out_fh, err_fh):
                if fh is not None:
                    fh.close()
        if rets[i] != 0:
            failure.set()

    try:
        for i, slot in enumerate(assignments):
            t = threading.Thread(target=run_slot, args=(i, slot), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
    finally:
        rendezvous.stop()
    bad = [(assignments[i].rank, r) for i, r in enumerate(rets) if r]
    if bad:
        print(f"horovodrun: ranks failed: {bad}", file=sys.stderr)
        return bad[0][1] or 1
    return 0


def _jsrun_spawn(args, assignments, base_env, addr, port,
                 coordinator) -> int:
    """Spawn every rank with ONE jsrun invocation (js_run.py:34 js_run).

    The ERF rankfile (js_run.py:96 generate_jsrun_rankfile) pins each
    rank to its assigned host; per-rank worker env comes from the
    rendezvous ``rank/{n}`` records via the jsrun_shim (jsrun starts all
    tasks with an identical command line, so the shim is how rank
    identity reaches the worker — the reference gets it from the MPI
    runtime instead).  The reference's cpu-range math rides on Summit's
    CSM queries; without CSM the ERF carries host pinning only and
    ``--binding-args`` (if given) is passed through verbatim."""
    import shlex
    import tempfile

    rankfile = None
    if getattr(args, "binding_args", None):
        # User-supplied binding replaces the generated rankfile entirely;
        # it must still start exactly len(assignments) tasks — the shim
        # checks its JSM world size against the slot record and fails
        # fast on a mismatch instead of hanging the collective.
        binding = shlex.split(args.binding_args)
    else:
        fd, rankfile = tempfile.mkstemp(prefix="hvd_tpu_erf_",
                                        suffix=".txt")
        with os.fdopen(fd, "w") as f:
            f.write("overlapping_rs: allow\ncpu_index_using: logical\n")
            for slot in assignments:
                f.write(f"rank: {slot.rank}: "
                        f"{{ hostname: {slot.hostname} }}\n")
        binding = ["--erf_input", rankfile]
    env = dict(base_env)
    env.update({
        _config.HOROVOD_RENDEZVOUS_ADDR: addr,
        _config.HOROVOD_RENDEZVOUS_PORT: str(port),
        "HVD_TPU_COORDINATOR": coordinator,
    })
    if args.output_filename:
        # Keep --output-filename's per-rank directory contract (rank.N/
        # stdout|stderr): the SHIM redirects each task — jsrun's
        # --stdio_* flags write one interleaved file, a different shape.
        env["HVD_TPU_OUTPUT_DIR"] = args.output_filename
    cmd = (["jsrun"] + binding
           + [sys.executable, "-m", "horovod_tpu.runner.jsrun_shim"]
           + args.command)
    if args.verbose:
        print("horovodrun: " + " ".join(shlex.quote(c) for c in cmd),
              file=sys.stderr)
    try:
        return safe_shell_exec.execute(cmd, env=env)
    finally:
        if rankfile is not None:
            try:
                os.remove(rankfile)
            except OSError:
                pass


def _run_elastic(args) -> int:
    """Elastic launch (launch.py:689): delegate to the elastic driver."""
    from ..elastic.driver import launch_elastic
    return launch_elastic(args)


def pick_coordinator_base_port(coordinator_host_is_local: bool) -> None:
    """Default the jax.distributed coordinator BASE port to a free one.

    A fixed default (29400) collides across successive or concurrent jobs
    on one host — e.g. orphaned workers of a killed launcher still bound
    to the old job's coordinator ports livelock the next job's
    registration.  Elastic world incarnations derive their ports from
    this base (elastic.coordinator_port_for), so the whole derived range
    moves with it.  An explicit HVD_TPU_COORD_PORT still wins (multi-host
    jobs where remote firewalls need a pinned port).

    Only applies when the coordinator (rank 0) runs on THIS host — the
    bind probe says nothing about a remote rank-0 host's port space, so
    multi-host jobs keep the pinned default."""
    if os.environ.get("HVD_TPU_COORD_PORT") or not coordinator_host_is_local:
        return
    port = None
    for _ in range(16):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("", 0))
            cand = s.getsockname()[1]
        finally:
            s.close()
        # Derived incarnation ports span [base, base+2000); keep the whole
        # range inside the valid port space.
        if cand <= 63500:
            port = cand
            break
    if port is None:
        import random
        port = random.randint(20000, 40000)
    os.environ["HVD_TPU_COORD_PORT"] = str(port)


def _run(args) -> int:
    if not args.command:
        print("horovodrun: no command given; see --help", file=sys.stderr)
        return 2
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    elastic = args.host_discovery_script is not None or \
        (args.min_np is not None and args.min_np != (args.max_np or args.min_np))
    if elastic:
        return _run_elastic(args)
    return _run_static(args)


def run_commandline(argv=None) -> None:
    sys.exit(_run(parse_args(argv)))


if __name__ == "__main__":
    run_commandline()
