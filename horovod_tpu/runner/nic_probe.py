"""NIC probing + cross-host interface intersection.

Reference: horovod/runner/driver/driver_service.py:122-194 — the launcher
starts a transient task probe on every host (ssh), learns each host's
network interfaces, and determines which launcher address every host can
actually reach, so the rendezvous/coordinator traffic uses an interface
the whole job shares.

Design (one round trip, nothing lingers): the launcher passes its FULL
candidate address list to the ssh-launched probe; the probe tries each
candidate against the live rendezvous KV port — the reachability test IS
the registration path, so there is no tautology and no separate check
phase — and PUTs one report ``{interfaces, reachable, addr}`` through
whichever candidate worked, then exits.  The launcher intersects
interface names and picks the first candidate present in every host's
reachable set.

Module CLI (what the launcher ssh-launches on each remote host)::

    python -m horovod_tpu.runner.nic_probe \
        --candidates 10.0.0.5:41231,192.168.1.5:41231 --host h1
"""

from __future__ import annotations

import json
import socket
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PROBE_SCOPE = "nicprobe"


def local_interfaces(include_loopback: bool = False) -> Dict[str, List[str]]:
    """iface -> IPv4 addresses on this host (the task-service NIC report).
    Uses psutil when present; degrades to a hostname-resolution singleton
    otherwise (psutil ships in this image but is an optional extra)."""
    try:
        import psutil
    except ImportError:
        try:
            return {"default": [socket.gethostbyname(socket.gethostname())]}
        except OSError:
            return {}
    out: Dict[str, List[str]] = {}
    for name, addrs in psutil.net_if_addrs().items():
        v4 = [a.address for a in addrs if a.family == socket.AF_INET]
        if not v4:
            continue
        if not include_loopback and all(a.startswith("127.") for a in v4):
            continue
        out[name] = v4
    return out


def addr_for_interfaces(nics: Sequence[str]) -> Optional[str]:
    """First local IPv4 address on the named interfaces
    (--network-interface handling, the reference's explicit-NIC path)."""
    ifaces = local_interfaces(include_loopback=True)
    for nic in nics:
        for a in ifaces.get(nic, []):
            return a
    return None


def _source_addr_toward(addr: str, port: int) -> Optional[str]:
    """The local address the route toward ``addr`` uses (UDP-connect +
    getsockname — avoids gethostbyname's 127.0.1.1 trap on stock
    Debian/Ubuntu /etc/hosts entries)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((addr, port))
            return s.getsockname()[0]
    except OSError:
        return None


def _try_put(addr: str, port: int, path: str, body: bytes,
             timeout: float = 3.0) -> bool:
    import http.client
    try:
        conn = http.client.HTTPConnection(addr, port, timeout=timeout)
        try:
            conn.request("PUT", path, body=body)
            return conn.getresponse().status < 400
        finally:
            conn.close()
    except OSError:
        return False


def probe_and_report(host: str, candidates: Sequence[Tuple[str, int]],
                     interfaces: Optional[Dict[str, List[str]]] = None
                     ) -> bool:
    """Probe-side body: test every candidate launcher address against the
    live KV port (the reachability test doubles as the transport), then
    publish one report through any candidate that worked.  Returns whether
    a report was delivered."""
    reachable = [a for a, p in candidates
                 if _try_put(a, p, f"/{PROBE_SCOPE}/ping/{host}", b"1")]
    report = {
        "interfaces": interfaces if interfaces is not None
        else local_interfaces(),
        "reachable": reachable,
        "addr": (_source_addr_toward(*candidates[0])
                 if candidates else None),
    }
    body = json.dumps(report).encode()
    for a, p in candidates:
        if a in reachable and _try_put(a, p,
                                       f"/{PROBE_SCOPE}/report/{host}",
                                       body):
            return True
    return False


def discover_common_address(kv_server, remote_hosts: Sequence[str],
                            spawn_probe: Callable[[str], None],
                            candidate_addrs: Sequence[str],
                            candidate_port: int,
                            timeout: float = 30.0):
    """Launcher-side flow (driver_service.py:218 get_common_interfaces):
    launch a probe per remote host, wait for their reports, intersect
    interface names (including the launcher's own), and pick the first
    candidate address every host reported reachable.

    Returns (common_interface_names, routable_addr_or_None).  Probes exit
    on their own after reporting — nothing to retire."""
    import threading
    import time
    del candidate_port  # candidates are probed by the remote side
    for h in remote_hosts:
        threading.Thread(target=spawn_probe, args=(h,), daemon=True,
                         name=f"hvd-nicprobe-{h}").start()
    reports: Dict[str, dict] = {}
    deadline = time.time() + timeout
    while len(reports) < len(remote_hosts) and time.time() < deadline:
        for h in remote_hosts:
            if h in reports:
                continue
            raw = kv_server.get(PROBE_SCOPE, f"report/{h}")
            if raw:
                reports[h] = json.loads(raw)
        time.sleep(0.2)
    missing = [h for h in remote_hosts if h not in reports]
    if missing:
        raise TimeoutError(
            f"NIC probes never reported from {missing} (ssh reachability / "
            f"no candidate launcher address dialable from there?)")
    common = set(local_interfaces().keys())
    for rep in reports.values():
        common &= set(rep.get("interfaces", {}).keys())
    routable = None
    for a in candidate_addrs:
        if all(a in rep.get("reachable", ()) for rep in reports.values()):
            routable = a
            break
    return sorted(common), routable


def main(argv=None):  # CLI: the ssh-launched remote probe
    import argparse
    import sys
    p = argparse.ArgumentParser()
    p.add_argument("--candidates", required=True,
                   help="comma-separated launcher addr:port candidates")
    p.add_argument("--host", required=True, help="this host's name")
    args = p.parse_args(argv)
    candidates = []
    for c in args.candidates.split(","):
        addr, _, port = c.rpartition(":")
        candidates.append((addr, int(port)))
    ok = probe_and_report(args.host, candidates)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
