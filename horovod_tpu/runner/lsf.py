"""LSF allocation discovery + jsrun availability.

Reference: horovod/runner/util/lsf.py:35 (LSFUtils — detects LSF via
LSB_JOBID, resolves the allocation's compute hosts and per-host slot
counts) and horovod/runner/js_run.py:28 (is_jsrun_installed).  The
reference resolves host resources through Summit's CSM tools
(csm_allocation_query); this build reads LSF's own portable environment —
``LSB_DJOB_HOSTFILE`` (one line per granted slot) with ``LSB_MCPU_HOSTS``
("host1 n1 host2 n2 ...") as the fallback — which every LSF deployment
sets, CSM or not.  Per-host slot counts here are LSF's granted process
slots; on a TPU pod each slot hosts one chip-driving worker process.
"""

from __future__ import annotations

import os
import shutil
from typing import List

from . import hosts as _hosts


def using_lsf() -> bool:
    """True when this process runs inside an LSF job (util/lsf.py:35)."""
    return "LSB_JOBID" in os.environ


def is_jsrun_installed() -> bool:
    """True if the jsrun launcher is on PATH (js_run.py:28)."""
    return shutil.which("jsrun") is not None


def lsf_hosts() -> List[_hosts.HostInfo]:
    """The allocation's hosts with slot counts, first-seen order preserved
    (rank 0 lands on the first granted host, matching jsrun's ERF order).

    Raises ``RuntimeError`` outside an allocation or when neither LSF
    host variable is present."""
    if not using_lsf():
        raise RuntimeError("not inside an LSF allocation (LSB_JOBID unset)")
    counts: dict = {}
    hostfile = os.environ.get("LSB_DJOB_HOSTFILE")
    if hostfile and os.path.exists(hostfile):
        with open(hostfile) as f:
            for line in f:
                h = line.strip()
                if h:
                    counts[h] = counts.get(h, 0) + 1
    else:
        toks = os.environ.get("LSB_MCPU_HOSTS", "").split()
        for h, n in zip(toks[::2], toks[1::2]):
            counts[h] = counts.get(h, 0) + int(n)
    if not counts:
        raise RuntimeError(
            "LSF allocation exposes no hosts (neither LSB_DJOB_HOSTFILE "
            "nor LSB_MCPU_HOSTS is usable)")
    # Summit-style deployments list the BATCH node (where the job script —
    # i.e. this launcher — runs) first with one slot, ahead of the compute
    # nodes; the reference's CSM query returns compute nodes only.  Drop a
    # leading 1-slot entry matching this host when other hosts exist, so a
    # rank is never pinned to the batch node.  Opt out with
    # HVD_TPU_LSF_INCLUDE_LAUNCH_HOST=1 (clusters whose first host is a
    # real compute host with one granted slot).
    items = list(counts.items())
    if (len(items) > 1 and items[0][1] == 1
            and os.environ.get("HVD_TPU_LSF_INCLUDE_LAUNCH_HOST") != "1"):
        import socket
        first = items[0][0]
        me = socket.gethostname()
        if first == me or first == me.split(".")[0] or \
                first.split(".")[0] == me.split(".")[0]:
            items = items[1:]
    return [_hosts.HostInfo(h, n) for h, n in items]
