"""HTTP KV store + rendezvous server.

Reference: horovod/runner/http/http_server.py:35 (KVStoreHandler: PUT/GET
scoped key-value store), :152 (RendezvousHandler), :192 (RendezvousServer:
publishes the host allocation plan that workers read to discover their slot
info).  The Gloo context reads `HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT` to find it
(common/gloo/gloo_context.h:28-42).

TPU build role: the same rendezvous pattern bootstraps (a) worker env
validation, (b) `jax.distributed` coordinator discovery, and (c) the elastic
driver's dynamic slot info (elastic rendezvous returns per-(host,local_rank)
records that change across resets).
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..utils import get_logger


class _KVHandler(BaseHTTPRequestHandler):
    """Scoped KV store over PUT/GET (http_server.py:35 KVStoreHandler).

    HTTP/1.1 so clients keep one persistent connection per thread (the
    eager control plane issues one request per dispatch; per-request
    connection setup dominated its latency).  Every response carries an
    explicit Content-Length — without it a 1.1 keep-alive client would
    block waiting for connection close.

    TCP_NODELAY is mandatory on both ends: a successful GET is two socket
    writes (status+headers flush, then the body), and with Nagle on, the
    body write sits behind the peer's delayed ACK — measured 44 ms p50 per
    successful GET on loopback, which multiplied into ~830 ms
    negotiations at np=16 (the coordinator GETs every rank's request).
    With NODELAY the same GET is ~0.15 ms."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True  # TCP_NODELAY on accepted sockets

    def log_message(self, fmt, *args):  # silence default stderr spam
        get_logger().debug("kvstore: " + fmt % args)

    def _empty(self, code: int) -> None:
        self.send_response(code)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.cache_cond:
            scope_dict = self.server.cache.setdefault(self._scope(), {})
            scope_dict[self._key()] = value
            self.server.cache_cond.notify_all()  # wake long-poll waiters
        self._empty(200)

    def do_GET(self):
        key = self._key()
        if key == "":
            self._scope_scan()
            return
        # Long-poll: GET /{scope}/{key}?wait=<seconds> blocks until the key
        # exists (or the wait elapses -> 404).  This is what keeps the
        # control plane off the server's CPU at scale: a worker waiting for
        # a negotiation verdict costs ~1 request/second instead of a
        # 200-requests/second polling loop (measured: np=16 cached-dispatch
        # p50 went 64 ms -> <2 ms when pollers stopped starving the server).
        wait_s = 0.0
        from urllib.parse import parse_qs, urlsplit
        q = parse_qs(urlsplit(self.path).query)
        if "wait" in q:
            try:
                wait_s = min(float(q["wait"][0]), 60.0)
            except ValueError:
                wait_s = 0.0
        deadline = None
        with self.server.cache_cond:
            while True:
                value = self.server.cache.get(self._scope(), {}).get(key)
                if value is not None or wait_s <= 0:
                    break
                import time as _time
                now = _time.monotonic()
                if deadline is None:
                    deadline = now + wait_s
                if now >= deadline:
                    break
                self.server.cache_cond.wait(deadline - now)
        if value is None:
            self._empty(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def _scope_scan(self):
        # Scope scan: GET /{scope} returns the whole scope as JSON
        # {key: base64(value)} — one request where per-key polling
        # would be O(keys) (e.g. the elastic init barrier reading
        # every rank's presence each poll, or the negotiation
        # coordinator collecting every rank's request).
        import base64
        import json as _json
        with self.server.cache_lock:
            scope = dict(self.server.cache.get(self._scope(), {}))
        body = _json.dumps({
            k: base64.b64encode(v).decode("ascii")
            for k, v in scope.items()}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        with self.server.cache_lock:
            scope_dict = self.server.cache.get(self._scope())
            if scope_dict is not None:
                scope_dict.pop(self._key(), None)
                if not scope_dict:
                    # GC the emptied scope: per-(name, epoch) negotiation
                    # scopes would otherwise leak one dict per negotiation
                    # for the launcher's lifetime.
                    self.server.cache.pop(self._scope(), None)
        self._empty(200)

    def _path_parts(self):
        # Path segments are percent-encoded by KVStoreClient, so a literal
        # '?' or '/' in a scope/key round-trips instead of being parsed as
        # query/separator; the query (?wait=...) is split off first.
        from urllib.parse import unquote, urlsplit
        path = urlsplit(self.path).path
        return [unquote(p) for p in path.strip("/").split("/")]

    def _scope(self) -> str:
        parts = self._path_parts()
        return parts[0] if parts else ""

    def _key(self) -> str:
        parts = self._path_parts()
        return "/".join(parts[1:]) if len(parts) > 1 else ""


class KVStoreServer:
    """Threaded KV server (RendezvousServer base, http_server.py:192)."""

    def __init__(self, verbose: bool = False):
        self.httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, port: int = 0) -> int:
        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self.httpd.cache = {}
        self.httpd.cache_lock = threading.Lock()
        # Long-poll waiters sleep on this condition (same lock); every PUT
        # notifies.  daemon_threads so a blocked long-poll never prevents
        # interpreter exit.
        self.httpd.cache_cond = threading.Condition(self.httpd.cache_lock)
        self.httpd.daemon_threads = True
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="hvd-kvstore")
        self._thread.start()
        return self.httpd.server_address[1]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def put(self, scope: str, key: str, value: bytes):
        with self.httpd.cache_cond:
            self.httpd.cache.setdefault(scope, {})[key] = value
            self.httpd.cache_cond.notify_all()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self.httpd.cache_lock:
            return self.httpd.cache.get(scope, {}).get(key)

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None


class RendezvousServer(KVStoreServer):
    """Publishes the host allocation plan (http_server.py:192
    RendezvousServer.init)."""

    SCOPE = "rendezvous"

    def init(self, host_alloc_plan) -> None:
        """host_alloc_plan: list of SlotInfo (runner/hosts.py).  Keys are
        published both by rank and by (hostname, local_rank) like the
        reference's elastic handler."""
        for slot in host_alloc_plan:
            payload = json.dumps(slot.to_dict()).encode()
            self.put(self.SCOPE, f"rank/{slot.rank}", payload)
            self.put(self.SCOPE,
                     f"slot/{slot.hostname}/{slot.local_rank}", payload)
        self.put(self.SCOPE, "size",
                 str(len(host_alloc_plan)).encode())


class KVStoreClient:
    """Worker-side client (runner/http/http_client.py analog).

    Keeps one persistent HTTP/1.1 connection per thread: the control plane
    issues a KV request per eager dispatch (ops/negotiation.py
    publish_dispatch), and per-request connection setup tripled its cost
    (~1.5 ms → ~0.4 ms with keep-alive).  Stale/broken connections are
    re-opened once per request."""

    def __init__(self, addr: str, port: int):
        self.addr = addr
        self.port = port
        self.base = f"http://{addr}:{port}"
        import threading
        self._local = threading.local()

    def _conn(self, fresh: bool = False):
        import http.client
        conn = getattr(self._local, "conn", None)
        if conn is None or fresh:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            conn = http.client.HTTPConnection(self.addr, self.port,
                                              timeout=30)
            conn.connect()
            # Mirror the server's TCP_NODELAY (see _KVHandler docstring).
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.conn = conn
        return conn

    @staticmethod
    def _path(scope: str, key: str = "") -> str:
        """Percent-encode each segment so scopes/keys with '?', '#', '%',
        spaces or non-URL bytes round-trip (tensor names are user input);
        '/' inside keys stays a segment separator, matching the server's
        split-then-unquote."""
        from urllib.parse import quote
        enc = quote(scope, safe="")
        if key:
            enc += "/" + "/".join(quote(p, safe="")
                                  for p in key.split("/"))
        return "/" + enc

    def _request(self, method: str, path: str, body: Optional[bytes] = None):
        import http.client
        for attempt in (0, 1):
            conn = self._conn(fresh=attempt > 0)
            try:
                conn.request(method, path, body=body)
                resp = conn.getresponse()
                data = resp.read()  # drain so the connection is reusable
                return resp.status, data
            except (http.client.HTTPException, ConnectionError, OSError):
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def put(self, scope: str, key: str, value: bytes):
        status, _ = self._request("PUT", self._path(scope, key), body=value)
        if status >= 400:
            raise OSError(f"KV put {scope}/{key} failed: HTTP {status}")

    def get(self, scope: str, key: str,
            wait: float = 0.0) -> Optional[bytes]:
        """``wait`` > 0 long-polls: the server holds the request until the
        key exists or the wait elapses (then 404 -> None).  One long-poll
        replaces hundreds of poll requests — the difference between a
        healthy and a saturated control plane at np >= 16."""
        path = self._path(scope, key)
        if wait > 0:
            # Stay well under the 30 s client socket timeout.
            path += f"?wait={min(wait, 25.0):.3f}"
        status, data = self._request("GET", path)
        if status == 404:
            return None
        if status >= 400:
            raise OSError(f"KV get {scope}/{key} failed: HTTP {status}")
        return data

    def delete(self, scope: str, key: str) -> None:
        status, _ = self._request("DELETE", self._path(scope, key))
        if status >= 400 and status != 404:
            raise OSError(f"KV delete {scope}/{key} failed: HTTP {status}")

    def scan(self, scope: str) -> dict:
        """Fetch a whole scope in ONE request: {key: value-bytes}."""
        import base64
        status, data = self._request("GET", self._path(scope))
        if status >= 400:
            raise OSError(f"KV scan {scope} failed: HTTP {status}")
        return {k: base64.b64decode(v)
                for k, v in json.loads(data or b"{}").items()}
