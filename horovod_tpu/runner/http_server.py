"""HTTP KV store + rendezvous server.

Reference: horovod/runner/http/http_server.py:35 (KVStoreHandler: PUT/GET
scoped key-value store), :152 (RendezvousHandler), :192 (RendezvousServer:
publishes the host allocation plan that workers read to discover their slot
info).  The Gloo context reads `HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT` to find it
(common/gloo/gloo_context.h:28-42).

TPU build role: the same rendezvous pattern bootstraps (a) worker env
validation, (b) `jax.distributed` coordinator discovery, and (c) the elastic
driver's dynamic slot info (elastic rendezvous returns per-(host,local_rank)
records that change across resets).
"""

from __future__ import annotations

import json
import os
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..utils import get_logger


class _KVHandler(BaseHTTPRequestHandler):
    """Scoped KV store over PUT/GET (http_server.py:35 KVStoreHandler).

    HTTP/1.1 so clients keep one persistent connection per thread (the
    eager control plane issues one request per dispatch; per-request
    connection setup dominated its latency).  Every response carries an
    explicit Content-Length — without it a 1.1 keep-alive client would
    block waiting for connection close.

    TCP_NODELAY is mandatory on both ends: a successful GET is two socket
    writes (status+headers flush, then the body), and with Nagle on, the
    body write sits behind the peer's delayed ACK — measured 44 ms p50 per
    successful GET on loopback, which multiplied into ~830 ms
    negotiations at np=16 (the coordinator GETs every rank's request).
    With NODELAY the same GET is ~0.15 ms."""

    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True  # TCP_NODELAY on accepted sockets

    def log_message(self, fmt, *args):  # silence default stderr spam
        get_logger().debug("kvstore: " + fmt % args)

    def _empty(self, code: int) -> None:
        self.send_response(code)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _cond(self, scope: str):
        """Per-scope condition (all sharing the cache lock): a PUT wakes
        only the waiters of ITS scope.  With one global condition every
        request-PUT woke every verdict waiter in the world — at np=16 a
        thundering herd of ~size^2 wakeups per negotiation."""
        return self.server.scope_conds.setdefault(
            scope, threading.Condition(self.server.cache_lock))

    def _notify(self, scope: str) -> None:
        c = self.server.scope_conds.get(scope)
        if c is not None:
            c.notify_all()

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.cache_lock:
            scope_dict = self.server.cache.setdefault(self._scope(), {})
            scope_dict[self._key()] = value
            self._notify(self._scope())  # wake this scope's waiters
        self._empty(200)

    def do_POST(self):
        if self._key():
            self._put_wait()
            return
        # Batch put: POST /{scope} with JSON {key: base64(value)} writes
        # every pair under one lock acquisition and one wakeup.  This is
        # the transport for the eager engine's per-cycle dispatch-stream
        # flush (ops/negotiation.py): one request carries a whole cycle's
        # records instead of one request per dispatch — the single
        # highest-volume stream on the control plane.
        import base64
        length = int(self.headers.get("Content-Length", 0))
        try:
            items = json.loads(self.rfile.read(length) or b"{}")
        except ValueError:
            self._empty(400)
            return
        with self.server.cache_lock:
            scope_dict = self.server.cache.setdefault(self._scope(), {})
            for k, v in items.items():
                scope_dict[k] = base64.b64decode(v)
            self._notify(self._scope())
        self._empty(200)

    def _put_wait(self):
        # Put-then-await: POST /{scope}/{key}?ascope=S&akey=K&wait=s stores
        # the body at scope/key, then holds the request until S/K exists
        # and returns its value (404 on timeout).  This folds a worker's
        # "announce my negotiation request, then long-poll the verdict"
        # into ONE round-trip — at np=16 on a single server the request
        # COUNT is the latency floor, so halving the per-rank requests
        # halves new-signature negotiation time.
        import time as _time
        from urllib.parse import parse_qs, urlsplit
        q = parse_qs(urlsplit(self.path).query)
        try:
            ascope = q["ascope"][0]
            akey = q["akey"][0]
        except (KeyError, IndexError):
            self._empty(400)
            return
        try:
            wait_s = min(float(q.get("wait", ["0"])[0]), 60.0)
        except ValueError:
            wait_s = 0.0
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        deadline = None
        with self.server.cache_lock:
            self.server.cache.setdefault(self._scope(), {})[self._key()] = \
                value
            self._notify(self._scope())
            while True:
                out = self.server.cache.get(ascope, {}).get(akey)
                if out is not None:
                    break
                now = _time.monotonic()
                if deadline is None:
                    deadline = now + wait_s
                if now >= deadline:
                    break
                self._cond(ascope).wait(deadline - now)
        if out is None:
            self._empty(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def do_GET(self):
        key = self._key()
        if key == "":
            self._scope_scan()
            return
        # Long-poll: GET /{scope}/{key}?wait=<seconds> blocks until the key
        # exists (or the wait elapses -> 404).  This is what keeps the
        # control plane off the server's CPU at scale: a worker waiting for
        # a negotiation verdict costs ~1 request/second instead of a
        # 200-requests/second polling loop (measured: np=16 cached-dispatch
        # p50 went 64 ms -> <2 ms when pollers stopped starving the server).
        wait_s = 0.0
        from urllib.parse import parse_qs, urlsplit
        q = parse_qs(urlsplit(self.path).query)
        if "wait" in q:
            try:
                wait_s = min(float(q["wait"][0]), 60.0)
            except ValueError:
                wait_s = 0.0
        deadline = None
        with self.server.cache_lock:
            while True:
                value = self.server.cache.get(self._scope(), {}).get(key)
                if value is not None or wait_s <= 0:
                    break
                import time as _time
                now = _time.monotonic()
                if deadline is None:
                    deadline = now + wait_s
                if now >= deadline:
                    break
                # Re-fetch each iteration: _gc_cond may have replaced the
                # scope's condition while this waiter slept.
                self._cond(self._scope()).wait(deadline - now)
        if value is None:
            self._empty(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def _scope_scan(self):
        # Scope scan: GET /{scope} returns the whole scope as JSON
        # {key: base64(value)} — one request where per-key polling
        # would be O(keys) (e.g. the elastic init barrier reading
        # every rank's presence each poll, or the negotiation
        # coordinator collecting every rank's request).
        #
        # Long-poll variant: GET /{scope}?min=N&wait=s holds the request
        # until the scope has >= N keys (or the wait elapses, returning
        # whatever is there).  The negotiation coordinator uses it to
        # collect all ranks' requests in ONE blocking request instead of a
        # sleep-scan loop whose 10 ms quantum put a floor under every
        # new-signature negotiation.
        import base64
        import json as _json
        import time as _time
        from urllib.parse import parse_qs, urlsplit
        q = parse_qs(urlsplit(self.path).query)
        min_keys, wait_s = 0, 0.0
        try:
            min_keys = int(q["min"][0]) if "min" in q else 0
            wait_s = min(float(q["wait"][0]), 60.0) if "wait" in q else 0.0
        except ValueError:
            pass
        deadline = None
        with self.server.cache_lock:
            while True:
                scope = self.server.cache.get(self._scope(), {})
                if min_keys <= 0 or len(scope) >= min_keys or wait_s <= 0:
                    scope = dict(scope)
                    break
                now = _time.monotonic()
                if deadline is None:
                    deadline = now + wait_s
                if now >= deadline:
                    scope = dict(scope)
                    break
                self._cond(self._scope()).wait(deadline - now)
        body = _json.dumps({
            k: base64.b64encode(v).decode("ascii")
            for k, v in scope.items()}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self):
        with self.server.cache_lock:
            key = self._key()
            if key == "":
                # Scope delete: DELETE /{scope} drops the whole scope in
                # one request (the negotiation coordinator GCs each
                # per-(name, epoch) request scope this way instead of every
                # rank deleting its own key).
                self.server.cache.pop(self._scope(), None)
                self._gc_cond(self._scope())
            else:
                scope_dict = self.server.cache.get(self._scope())
                if scope_dict is not None:
                    scope_dict.pop(key, None)
                    if not scope_dict:
                        # GC the emptied scope: per-(name, epoch)
                        # negotiation scopes would otherwise leak one dict
                        # per negotiation for the launcher's lifetime.
                        self.server.cache.pop(self._scope(), None)
                        self._gc_cond(self._scope())
        self._empty(200)

    def _gc_cond(self, scope: str) -> None:
        """Drop a deleted scope's condition (bounds memory to live scopes)
        after waking its waiters — a waiter left on the popped condition
        would otherwise sleep out its full timeout even if the key
        reappeared (the reappearing PUT creates a NEW condition).  Woken
        waiters re-check and, still-unsatisfied, time out their chunk and
        re-issue, re-entering on the fresh condition."""
        c = self.server.scope_conds.pop(scope, None)
        if c is not None:
            c.notify_all()

    def _path_parts(self):
        # Path segments are percent-encoded by KVStoreClient, so a literal
        # '?' or '/' in a scope/key round-trips instead of being parsed as
        # query/separator; the query (?wait=...) is split off first.
        from urllib.parse import unquote, urlsplit
        path = urlsplit(self.path).path
        return [unquote(p) for p in path.strip("/").split("/")]

    def _scope(self) -> str:
        parts = self._path_parts()
        return parts[0] if parts else ""

    def _key(self) -> str:
        parts = self._path_parts()
        return "/".join(parts[1:]) if len(parts) > 1 else ""


class KVStoreServer:
    """KV server (RendezvousServer base, http_server.py:192).

    Two interchangeable backends behind one API: the C++ server
    (csrc/kv_server.cc, default — per-request host CPU is ~10x cheaper,
    which is the control-plane latency floor at np >= 16 on a one-core
    launcher host) and this module's Python ``_KVHandler`` (fallback when
    the native build is unavailable, or forced with
    ``HVD_TPU_KV_SERVER=python``).  Both keep the store readable through
    ``get``/``scan_scope`` after ``stop()`` — launcher code gathers worker
    results after shutdown (runner/__init__.py)."""

    def __init__(self, verbose: bool = False):
        self.httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._native = None
        self._cache: Optional[dict] = None
        self._lock: Optional[threading.Lock] = None

    def start(self, port: int = 0) -> int:
        if os.environ.get("HVD_TPU_KV_SERVER", "native") != "python":
            try:
                from ..csrc import NativeKVServer
                native = NativeKVServer()
                bound = native.start(port)
                self._native = native
                return bound
            except Exception as e:
                get_logger().warning(
                    "native KV server unavailable (%s); falling back to "
                    "the Python server", e)
        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self.httpd.cache = self._cache = {}
        self.httpd.cache_lock = self._lock = threading.Lock()
        # Long-poll waiters sleep on per-scope conditions (all sharing the
        # cache lock); a PUT wakes only its scope's waiters.
        # daemon_threads so a blocked long-poll never prevents interpreter
        # exit.
        self.httpd.scope_conds = {}
        self.httpd.daemon_threads = True
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="hvd-kvstore")
        self._thread.start()
        return self.httpd.server_address[1]

    @property
    def port(self) -> int:
        if self._native is not None:
            return self._native.port
        return self.httpd.server_address[1]

    def put(self, scope: str, key: str, value: bytes):
        if self._native is not None:
            self._native.put(scope, key, value)
            return
        with self._lock:
            self._cache.setdefault(scope, {})[key] = value
            if self.httpd is not None:
                c = self.httpd.scope_conds.get(scope)
                if c is not None:
                    c.notify_all()

    def get(self, scope: str, key: str) -> Optional[bytes]:
        if self._native is not None:
            return self._native.get(scope, key)
        with self._lock:
            return self._cache.get(scope, {}).get(key)

    def scan_scope(self, scope: str) -> Dict[str, bytes]:
        """Server-side scope snapshot (no HTTP round-trip)."""
        if self._native is not None:
            return self._native.scan_scope(scope)
        with self._lock:
            return dict(self._cache.get(scope, {}))

    def stop(self):
        if self._native is not None:
            self._native.stop()
            return
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None
        if self._thread is not None:
            # serve_forever was told to exit; join it so stop() leaves no
            # acceptor thread behind (daemon=True stays the interpreter-
            # exit backstop — a handler blocked in a long-poll must never
            # pin exit, module doc).
            self._thread.join(timeout=10)
            if not self._thread.is_alive():
                self._thread = None


class RendezvousServer(KVStoreServer):
    """Publishes the host allocation plan (http_server.py:192
    RendezvousServer.init)."""

    SCOPE = "rendezvous"

    def init(self, host_alloc_plan) -> None:
        """host_alloc_plan: list of SlotInfo (runner/hosts.py).  Keys are
        published both by rank and by (hostname, local_rank) like the
        reference's elastic handler."""
        for slot in host_alloc_plan:
            payload = json.dumps(slot.to_dict()).encode()
            self.put(self.SCOPE, f"rank/{slot.rank}", payload)
            self.put(self.SCOPE,
                     f"slot/{slot.hostname}/{slot.local_rank}", payload)
        self.put(self.SCOPE, "size",
                 str(len(host_alloc_plan)).encode())


class KVStoreClient:
    """Worker-side client (runner/http/http_client.py analog).

    Keeps one persistent HTTP/1.1 connection per thread: the control plane
    issues a KV request per eager dispatch (ops/negotiation.py
    publish_dispatch), and per-request connection setup tripled its cost
    (~1.5 ms → ~0.4 ms with keep-alive).

    Transport errors are RETRIED with capped jittered exponential backoff
    (``HVD_KV_RETRY_MAX`` attempts total, delays ``HVD_KV_RETRY_BASE_MS``
    · 2^n capped at ``HVD_KV_RETRY_CAP_MS``, each scaled by a uniform
    [0.5, 1) jitter so a fleet retrying the same dead server doesn't
    stampede in lockstep): connect failures, timeouts, and mid-response
    disconnects are transient by nature — the KV server restarting or a
    link flapping — and every verb here is idempotent (PUT/GET/DELETE/
    scan; put_wait's re-put is its documented re-issue).  HTTP 4xx
    responses are FATAL and never retried: the server answered, the
    request itself is wrong, and retrying would just repeat the answer
    (callers raise OSError on them immediately)."""

    def __init__(self, addr: str, port: int):
        self.addr = addr
        self.port = port
        self.base = f"http://{addr}:{port}"
        import threading
        self._local = threading.local()
        self.retry_max = max(int(os.environ.get("HVD_KV_RETRY_MAX", "3")),
                             1)
        self.retry_base_s = float(
            os.environ.get("HVD_KV_RETRY_BASE_MS", "10")) / 1e3
        self.retry_cap_s = float(
            os.environ.get("HVD_KV_RETRY_CAP_MS", "2000")) / 1e3
        from ..faultline import runtime as _flrt
        _flrt.maybe_install_from_env()
        from ..obs import tracing as _tr
        _tr.maybe_install_from_env()

    def _retry_backoff_s(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based): capped exponential
        with jitter (class docstring)."""
        import random
        base = min(self.retry_base_s * (2 ** (attempt - 1)),
                   self.retry_cap_s)
        return base * (0.5 + random.random() / 2)

    def _conn(self, fresh: bool = False):
        sock = getattr(self._local, "sock", None)
        if sock is None or fresh:
            if sock is not None:
                try:
                    sock.close()
                except Exception:
                    pass
            sock = socket.create_connection((self.addr, self.port),
                                            timeout=30)
            # Mirror the server's TCP_NODELAY (see _KVHandler docstring).
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._local.sock = sock
            self._local.buf = b""
        return sock

    @staticmethod
    def _path(scope: str, key: str = "") -> str:
        """Percent-encode each segment so scopes/keys with '?', '#', '%',
        spaces or non-URL bytes round-trip (tensor names are user input);
        '/' inside keys stays a segment separator, matching the server's
        split-then-unquote."""
        from urllib.parse import quote
        enc = quote(scope, safe="")
        if key:
            enc += "/" + "/".join(quote(p, safe="")
                                  for p in key.split("/"))
        return "/" + enc

    def _request(self, method: str, path: str, body: Optional[bytes] = None):
        """Hand-rolled HTTP/1.1 over the persistent per-thread socket.
        ``http.client`` cost ~80 us of host CPU per request — on the
        launcher's one core that overhead, times np, IS the control-plane
        latency floor (csrc/kv_server.cc header); this minimal writer/parser
        runs ~25 us against the same servers."""
        import time as _time

        from ..faultline import runtime as _flrt
        from ..obs import tracing as _tr
        trace_ctx = None
        trace_extra = ""
        if _tr.TRACER is not None:
            # Wire propagation (docs/observability.md): a KV round-trip
            # issued while a traced request is active on this thread
            # carries the trace headers, and each RETRY attempt becomes
            # a kv-retry span — transport flakes show up inside the
            # request's own span tree.  One module-attribute read when
            # tracing is off.
            trace_ctx = _tr.current()
            if trace_ctx is not None:
                trace_extra = (
                    f"X-Trace-Id: {trace_ctx.trace_id}\r\n"
                    f"X-Parent-Span: {trace_ctx.span_id}\r\n")
        req = (f"{method} {path} HTTP/1.1\r\nHost: {self.addr}\r\n"
               f"{trace_extra}"
               f"Content-Length: {len(body) if body else 0}\r\n\r\n"
               .encode("ascii"))
        if body:
            req += body
        for attempt in range(self.retry_max):
            sock = None
            attempt_t0 = _time.monotonic()
            try:
                if _flrt.PLAN is not None:
                    # ``kv.request`` injection point (one consult per
                    # ATTEMPT, so a drop train of length n exercises n
                    # retries): delay-kv stalls the request, drop-kv-
                    # response fails it as a transport error — landing in
                    # the same retry path a real flake takes.
                    for f in _flrt.fire("kv.request",
                                        f"{self.addr}:{self.port}"):
                        if f.kind == "delay-kv":
                            _time.sleep(f.param or 0.02)
                        elif f.kind == "drop-kv-response":
                            raise ConnectionError(
                                "faultline: dropped KV response")
                sock = self._conn(fresh=attempt > 0)
                sock.sendall(req)
                return self._read_response(sock)
            except (ConnectionError, OSError) as e:
                if trace_ctx is not None and _tr.TRACER is not None:
                    try:
                        _tr.TRACER.emit_span(
                            trace_ctx, "kv-retry", attempt_t0,
                            _time.monotonic(), "kv-client",
                            args={"attempt": attempt + 1,
                                  "of": self.retry_max,
                                  "method": method,
                                  "error": str(e)[:120]})
                    except Exception:
                        pass
                if attempt + 1 >= self.retry_max:
                    # Out of budget.  Drop the desynced socket: a request
                    # went out, so a LATE response may still arrive — a
                    # later request reusing this socket would consume it
                    # as its own (http.client raised CannotSendRequest
                    # here; the raw-socket path must poison the
                    # connection itself).
                    if sock is not None:
                        try:
                            sock.close()
                        except Exception:
                            pass
                    self._local.sock = None
                    raise
                delay = self._retry_backoff_s(attempt + 1)
                get_logger().debug(
                    "KV %s %s attempt %d/%d failed (%s); retrying in "
                    "%.0f ms", method, path, attempt + 1, self.retry_max,
                    e, delay * 1e3)
                _time.sleep(delay)
        raise AssertionError("unreachable")

    def _read_response(self, sock):
        """Parse one response: status line + headers + Content-Length body
        (both servers always send Content-Length; leftover bytes stay in
        the per-thread buffer for the next response)."""
        buf = self._local.buf
        while True:
            end = buf.find(b"\r\n\r\n")
            if end >= 0:
                break
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("KV server closed the connection")
            buf += chunk
        head, rest = buf[:end], buf[end + 4:]
        status_line, _, header_block = head.partition(b"\r\n")
        status = int(status_line.split(b" ", 2)[1])
        clen = 0
        for line in header_block.split(b"\r\n"):
            if line[:15].lower() == b"content-length:":
                clen = int(line[15:])
                break
        while len(rest) < clen:
            chunk = sock.recv(65536)
            if not chunk:
                raise ConnectionError("KV server closed mid-body")
            rest += chunk
        self._local.buf = rest[clen:]
        return status, rest[:clen]

    def put(self, scope: str, key: str, value: bytes):
        status, _ = self._request("PUT", self._path(scope, key), body=value)
        if status >= 400:
            raise OSError(f"KV put {scope}/{key} failed: HTTP {status}")

    def put_batch(self, scope: str, items: Dict[str, bytes]) -> None:
        """Write many keys in ONE request (server applies them under one
        lock, in iteration order).  The eager dispatch-stream flusher rides
        this: a whole cycle's records cost one round-trip."""
        import base64
        body = json.dumps({
            k: base64.b64encode(v).decode("ascii")
            for k, v in items.items()}).encode()
        status, _ = self._request("POST", self._path(scope), body=body)
        if status >= 400:
            raise OSError(f"KV put_batch {scope} failed: HTTP {status}")

    def get(self, scope: str, key: str,
            wait: float = 0.0) -> Optional[bytes]:
        """``wait`` > 0 long-polls: the server holds the request until the
        key exists or the wait elapses (then 404 -> None).  One long-poll
        replaces hundreds of poll requests — the difference between a
        healthy and a saturated control plane at np >= 16."""
        path = self._path(scope, key)
        if wait > 0:
            # Stay well under the 30 s client socket timeout.
            path += f"?wait={min(wait, 25.0):.3f}"
        status, data = self._request("GET", path)
        if status == 404:
            return None
        if status >= 400:
            raise OSError(f"KV get {scope}/{key} failed: HTTP {status}")
        return data

    def put_wait(self, scope: str, key: str, value: bytes,
                 await_scope: str, await_key: str,
                 wait: float) -> Optional[bytes]:
        """Store ``value`` at scope/key, then block server-side until
        ``await_scope``/``await_key`` exists and return its value (None on
        timeout — re-issue; the re-put is idempotent).  One round-trip for
        the announce-request-then-await-verdict pattern."""
        from urllib.parse import quote
        path = (self._path(scope, key)
                + f"?ascope={quote(await_scope, safe='')}"
                + f"&akey={quote(await_key, safe='')}"
                + f"&wait={min(wait, 25.0):.3f}")
        status, data = self._request("POST", path, body=value)
        if status == 404:
            return None
        if status >= 400:
            raise OSError(f"KV put_wait {scope}/{key} failed: HTTP {status}")
        return data

    def delete(self, scope: str, key: str) -> None:
        status, _ = self._request("DELETE", self._path(scope, key))
        if status >= 400 and status != 404:
            raise OSError(f"KV delete {scope}/{key} failed: HTTP {status}")

    def delete_scope(self, scope: str) -> None:
        """Drop a whole scope in one request."""
        status, _ = self._request("DELETE", self._path(scope))
        if status >= 400 and status != 404:
            raise OSError(f"KV delete_scope {scope} failed: HTTP {status}")

    def scan(self, scope: str, wait: float = 0.0,
             min_keys: int = 0) -> dict:
        """Fetch a whole scope in ONE request: {key: value-bytes}.
        With ``min_keys`` > 0 and ``wait`` > 0, the server holds the
        request until the scope has at least that many keys (or the wait
        elapses — the caller re-checks and re-issues)."""
        import base64
        path = self._path(scope)
        if min_keys > 0 and wait > 0:
            path += f"?min={min_keys}&wait={min(wait, 25.0):.3f}"
        status, data = self._request("GET", path)
        if status >= 400:
            raise OSError(f"KV scan {scope} failed: HTTP {status}")
        return {k: base64.b64decode(v)
                for k, v in json.loads(data or b"{}").items()}
