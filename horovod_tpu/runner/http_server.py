"""HTTP KV store + rendezvous server.

Reference: horovod/runner/http/http_server.py:35 (KVStoreHandler: PUT/GET
scoped key-value store), :152 (RendezvousHandler), :192 (RendezvousServer:
publishes the host allocation plan that workers read to discover their slot
info).  The Gloo context reads `HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT` to find it
(common/gloo/gloo_context.h:28-42).

TPU build role: the same rendezvous pattern bootstraps (a) worker env
validation, (b) `jax.distributed` coordinator discovery, and (c) the elastic
driver's dynamic slot info (elastic rendezvous returns per-(host,local_rank)
records that change across resets).
"""

from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..utils import get_logger


class _KVHandler(BaseHTTPRequestHandler):
    """Scoped KV store over PUT/GET (http_server.py:35 KVStoreHandler).

    HTTP/1.1 so clients keep one persistent connection per thread (the
    eager control plane issues one request per dispatch; per-request
    connection setup dominated its latency).  Every response carries an
    explicit Content-Length — without it a 1.1 keep-alive client would
    block waiting for connection close."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence default stderr spam
        get_logger().debug("kvstore: " + fmt % args)

    def _empty(self, code: int) -> None:
        self.send_response(code)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        with self.server.cache_lock:
            scope_dict = self.server.cache.setdefault(self._scope(), {})
            scope_dict[self._key()] = value
        self._empty(200)

    def do_GET(self):
        key = self._key()
        if key == "":
            # Scope scan: GET /{scope} returns the whole scope as JSON
            # {key: base64(value)} — one request where per-key polling
            # would be O(keys) (e.g. the elastic init barrier reading
            # every rank's presence each poll).
            import base64
            import json as _json
            with self.server.cache_lock:
                scope = dict(self.server.cache.get(self._scope(), {}))
            body = _json.dumps({
                k: base64.b64encode(v).decode("ascii")
                for k, v in scope.items()}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        with self.server.cache_lock:
            value = self.server.cache.get(self._scope(), {}).get(key)
        if value is None:
            self._empty(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_DELETE(self):
        with self.server.cache_lock:
            self.server.cache.get(self._scope(), {}).pop(self._key(), None)
        self._empty(200)

    def _scope(self) -> str:
        parts = self.path.strip("/").split("/")
        return parts[0] if parts else ""

    def _key(self) -> str:
        parts = self.path.strip("/").split("/")
        return "/".join(parts[1:]) if len(parts) > 1 else ""


class KVStoreServer:
    """Threaded KV server (RendezvousServer base, http_server.py:192)."""

    def __init__(self, verbose: bool = False):
        self.httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self, port: int = 0) -> int:
        self.httpd = ThreadingHTTPServer(("0.0.0.0", port), _KVHandler)
        self.httpd.cache = {}
        self.httpd.cache_lock = threading.Lock()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="hvd-kvstore")
        self._thread.start()
        return self.httpd.server_address[1]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def put(self, scope: str, key: str, value: bytes):
        with self.httpd.cache_lock:
            self.httpd.cache.setdefault(scope, {})[key] = value

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self.httpd.cache_lock:
            return self.httpd.cache.get(scope, {}).get(key)

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None


class RendezvousServer(KVStoreServer):
    """Publishes the host allocation plan (http_server.py:192
    RendezvousServer.init)."""

    SCOPE = "rendezvous"

    def init(self, host_alloc_plan) -> None:
        """host_alloc_plan: list of SlotInfo (runner/hosts.py).  Keys are
        published both by rank and by (hostname, local_rank) like the
        reference's elastic handler."""
        for slot in host_alloc_plan:
            payload = json.dumps(slot.to_dict()).encode()
            self.put(self.SCOPE, f"rank/{slot.rank}", payload)
            self.put(self.SCOPE,
                     f"slot/{slot.hostname}/{slot.local_rank}", payload)
        self.put(self.SCOPE, "size",
                 str(len(host_alloc_plan)).encode())


class KVStoreClient:
    """Worker-side client (runner/http/http_client.py analog).

    Keeps one persistent HTTP/1.1 connection per thread: the control plane
    issues a KV request per eager dispatch (ops/negotiation.py
    publish_dispatch), and per-request connection setup tripled its cost
    (~1.5 ms → ~0.4 ms with keep-alive).  Stale/broken connections are
    re-opened once per request."""

    def __init__(self, addr: str, port: int):
        self.addr = addr
        self.port = port
        self.base = f"http://{addr}:{port}"
        import threading
        self._local = threading.local()

    def _conn(self, fresh: bool = False):
        import http.client
        conn = getattr(self._local, "conn", None)
        if conn is None or fresh:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
            conn = http.client.HTTPConnection(self.addr, self.port,
                                              timeout=30)
            self._local.conn = conn
        return conn

    def _request(self, method: str, path: str, body: Optional[bytes] = None):
        import http.client
        for attempt in (0, 1):
            conn = self._conn(fresh=attempt > 0)
            try:
                conn.request(method, path, body=body)
                resp = conn.getresponse()
                data = resp.read()  # drain so the connection is reusable
                return resp.status, data
            except (http.client.HTTPException, ConnectionError, OSError):
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def put(self, scope: str, key: str, value: bytes):
        status, _ = self._request("PUT", f"/{scope}/{key}", body=value)
        if status >= 400:
            raise OSError(f"KV put {scope}/{key} failed: HTTP {status}")

    def get(self, scope: str, key: str) -> Optional[bytes]:
        status, data = self._request("GET", f"/{scope}/{key}")
        if status == 404:
            return None
        if status >= 400:
            raise OSError(f"KV get {scope}/{key} failed: HTTP {status}")
        return data

    def delete(self, scope: str, key: str) -> None:
        status, _ = self._request("DELETE", f"/{scope}/{key}")
        if status >= 400 and status != 404:
            raise OSError(f"KV delete {scope}/{key} failed: HTTP {status}")

    def scan(self, scope: str) -> dict:
        """Fetch a whole scope in ONE request: {key: value-bytes}."""
        import base64
        status, data = self._request("GET", f"/{scope}")
        if status >= 400:
            raise OSError(f"KV scan {scope} failed: HTTP {status}")
        return {k: base64.b64decode(v)
                for k, v in json.loads(data or b"{}").items()}
