"""In-process launcher API: ``horovod_tpu.run(func, np=...)``.

Reference: horovod/runner/__init__.py:95 ``horovod.run`` — pickles the
function (cloudpickle), launches workers, ships the function via the
rendezvous KV store, gathers per-rank return values.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional

from .launch import parse_args, _run_static


def run(func: Callable,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        np: int = 1,
        hosts: Optional[str] = None,
        hostfile: Optional[str] = None,
        start_timeout: Optional[int] = None,
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        verbose: bool = False,
        use_gloo: Optional[bool] = None,
        use_mpi: Optional[bool] = None,
        network_interface: Optional[str] = None) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` ranks and return the list of
    per-rank results ordered by rank (horovod.run, runner/__init__.py:95).

    The function is cloudpickled to a temp file, each worker executes a
    bootstrap that initializes the runtime, calls it, and writes its result
    to ``result_<rank>.pkl``; the launcher collects them.
    """
    import cloudpickle
    from . import hosts as _hosts_mod
    from .launch import _is_local
    kwargs = kwargs or {}
    has_remote = bool(hosts) and any(
        not _is_local(h.hostname) for h in _hosts_mod.parse_hosts(hosts))
    if has_remote:
        # Remote workers cd into this cwd over ssh (launch._ssh_command),
        # so a cwd-anchored workdir is readable exactly when the job's
        # working tree is on a shared mount — the reference's assumption
        # for shipping the pickled function.  /tmp is per-machine.
        base = os.path.join(os.getcwd(), ".hvd_tpu_run")
        os.makedirs(base, exist_ok=True)
        workdir = tempfile.mkdtemp(prefix="run_", dir=base)
        from ..utils import get_logger
        get_logger().warning(
            "run(): remote hosts %s read the pickled function from %s — "
            "the working tree must be a shared mount",
            [h.hostname for h in _hosts_mod.parse_hosts(hosts)
             if not _is_local(h.hostname)], workdir)
    else:
        workdir = tempfile.mkdtemp(prefix="hvd_tpu_run_")
    fn_path = os.path.join(workdir, "func.pkl")
    with open(fn_path, "wb") as f:
        cloudpickle.dump((func, args, kwargs), f)

    # Workers must resolve the same modules the caller sees (the pickled
    # function is serialized by reference when its module is importable):
    # ship the parent's full sys.path, not just its cwd.
    parent_path = [p for p in [os.getcwd()] + sys.path if p]
    # Results travel back through the launcher's rendezvous KV store
    # (runner/__init__.py:95 reference contract) so REMOTE ranks work too;
    # the temp-dir file is kept as a local-host fallback.  The function
    # itself ships via a shared-filesystem path like the reference's
    # cloudpickle-through-KV (remote hosts need the repo + workdir mounted).
    bootstrap = f"""
import pickle, os, sys, urllib.request
sys.path[:0] = [p for p in {parent_path!r} if p not in sys.path]
try:
    fh = open({fn_path!r}, 'rb')
except FileNotFoundError:
    print('horovod_tpu.run: cannot read the pickled function at '
          {fn_path!r} + ' — remote hosts need the launcher working tree '
          'on a SHARED mount (the function ships via the filesystem; '
          'results return via the rendezvous KV)', file=sys.stderr)
    raise
fn, a, kw = pickle.load(fh)
r = fn(*a, **kw)
rank = int(os.environ.get('HOROVOD_RANK', 0))
payload = pickle.dumps(r)
sent = False
try:
    addr = os.environ['HOROVOD_GLOO_RENDEZVOUS_ADDR']
    port = os.environ['HOROVOD_GLOO_RENDEZVOUS_PORT']
    req = urllib.request.Request(
        'http://%s:%s/runresults/%d' % (addr, port, rank),
        data=payload, method='PUT')
    urllib.request.urlopen(req, timeout=30).read()
    sent = True
except Exception as e:
    print('result KV put failed: %r' % (e,), file=sys.stderr)
if not sent:
    open(os.path.join({workdir!r}, 'result_%d.pkl' % rank), 'wb') \\
        .write(payload)
"""
    argv = ["-np", str(np)]
    if hosts:
        argv += ["-H", hosts]
    if hostfile:
        argv += ["--hostfile", hostfile]
    if ssh_port:
        argv += ["-p", str(ssh_port)]
    if ssh_identity_file:
        argv += ["-i", ssh_identity_file]
    if verbose:
        argv += ["--verbose"]
    argv += [sys.executable, "-c", bootstrap]
    parsed = parse_args(argv)
    captured = {}

    def _capture(rendezvous):
        # The store outlives the server shutdown (KVStoreServer keeps it
        # readable post-stop, whichever backend serves it).
        captured["server"] = rendezvous

    try:
        ret = _run_static(parsed, on_rendezvous=_capture)
        if ret != 0:
            raise RuntimeError(
                f"horovod_tpu.run failed with exit code {ret}")
        srv = captured.get("server")
        kv_results = srv.scan_scope("runresults") if srv is not None else {}
        results = []
        for rank in range(np):
            raw = kv_results.get(str(rank))
            if raw is not None:
                results.append(pickle.loads(raw))
                continue
            path = os.path.join(workdir, f"result_{rank}.pkl")
            with open(path, "rb") as f:
                results.append(pickle.load(f))
        return results
    finally:
        # The staged function pickle can embed caller data; it must not
        # linger (especially on a shared mount) on ANY exit path.
        import shutil
        shutil.rmtree(workdir, ignore_errors=True)
