"""In-process launcher API: ``horovod_tpu.run(func, np=...)``.

Reference: horovod/runner/__init__.py:95 ``horovod.run`` — pickles the
function (cloudpickle), launches workers, ships the function via the
rendezvous KV store, gathers per-rank return values.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
from typing import Any, Callable, List, Optional

from .launch import parse_args, _run_static


def run(func: Callable,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        np: int = 1,
        hosts: Optional[str] = None,
        hostfile: Optional[str] = None,
        start_timeout: Optional[int] = None,
        ssh_port: Optional[int] = None,
        ssh_identity_file: Optional[str] = None,
        verbose: bool = False,
        use_gloo: Optional[bool] = None,
        use_mpi: Optional[bool] = None,
        network_interface: Optional[str] = None) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on ``np`` ranks and return the list of
    per-rank results ordered by rank (horovod.run, runner/__init__.py:95).

    The function is cloudpickled to a temp file, each worker executes a
    bootstrap that initializes the runtime, calls it, and writes its result
    to ``result_<rank>.pkl``; the launcher collects them.
    """
    import cloudpickle
    from . import hosts as _hosts_mod
    from .launch import _is_local
    if hosts:
        remote = [h.hostname for h in _hosts_mod.parse_hosts(hosts)
                  if not _is_local(h.hostname)]
        if remote:
            raise NotImplementedError(
                f"horovod_tpu.run() currently gathers results through a "
                f"local temp dir and cannot collect from remote hosts "
                f"{remote}; use the horovodrun CLI with a shared filesystem "
                f"instead")
    kwargs = kwargs or {}
    workdir = tempfile.mkdtemp(prefix="hvd_tpu_run_")
    fn_path = os.path.join(workdir, "func.pkl")
    with open(fn_path, "wb") as f:
        cloudpickle.dump((func, args, kwargs), f)

    # Workers must resolve the same modules the caller sees (the pickled
    # function is serialized by reference when its module is importable):
    # ship the parent's full sys.path, not just its cwd.
    parent_path = [p for p in [os.getcwd()] + sys.path if p]
    bootstrap = (
        "import pickle, os, sys; "
        f"sys.path[:0] = [p for p in {parent_path!r} if p not in sys.path]; "
        f"fn, a, kw = pickle.load(open({fn_path!r}, 'rb')); "
        "r = fn(*a, **kw); "
        "rank = int(os.environ.get('HOROVOD_RANK', 0)); "
        f"pickle.dump(r, open(os.path.join({workdir!r}, "
        "f'result_{rank}.pkl'), 'wb'))"
    )
    argv = ["-np", str(np)]
    if hosts:
        argv += ["-H", hosts]
    if hostfile:
        argv += ["--hostfile", hostfile]
    if ssh_port:
        argv += ["-p", str(ssh_port)]
    if ssh_identity_file:
        argv += ["-i", ssh_identity_file]
    if verbose:
        argv += ["--verbose"]
    argv += [sys.executable, "-c", bootstrap]
    parsed = parse_args(argv)
    ret = _run_static(parsed)
    if ret != 0:
        raise RuntimeError(f"horovod_tpu.run failed with exit code {ret}")
    results = []
    for rank in range(np):
        path = os.path.join(workdir, f"result_{rank}.pkl")
        with open(path, "rb") as f:
            results.append(pickle.load(f))
    return results
