"""Host spec parsing + rank assignment.

Reference: horovod/runner/common/util/hosts.py:22 (parse_hosts: "h1:4,h2:4"),
:34 (parse_host_files), :100 (get_host_assignments: round-robin rank →
(host, slot) with local/cross rank computation).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        if ":" in host_string:
            name, slots = host_string.rsplit(":", 1)
            return HostInfo(name.strip(), int(slots))
        return HostInfo(host_string.strip(), 1)


@dataclasses.dataclass
class SlotInfo:
    """One rank's placement (runner/common/util/hosts.py SlotInfo)."""
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "SlotInfo":
        return SlotInfo(**d)

    def env(self) -> Dict[str, str]:
        """The per-worker HOROVOD_* identity env for this slot — the ONE
        place that owns the slot-to-env contract (used by the launcher's
        per-slot spawn and by the jsrun shim; gloo_run.py:66-78)."""
        from .. import config as _config
        return {
            _config.HOROVOD_RANK: str(self.rank),
            _config.HOROVOD_SIZE: str(self.size),
            _config.HOROVOD_LOCAL_RANK: str(self.local_rank),
            _config.HOROVOD_LOCAL_SIZE: str(self.local_size),
            _config.HOROVOD_CROSS_RANK: str(self.cross_rank),
            _config.HOROVOD_CROSS_SIZE: str(self.cross_size),
            _config.HOROVOD_HOSTNAME: self.hostname,
        }


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """"host1:2,host2:4" → [HostInfo] (hosts.py:22)."""
    return [HostInfo.from_string(h)
            for h in hosts_string.split(",") if h.strip()]


def parse_host_files(filename: str) -> List[HostInfo]:
    """Hostfile with "hostname slots=N" lines (hosts.py:34)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            hosts.append(HostInfo(name, slots))
    return hosts


def get_host_assignments(hosts: List[HostInfo], min_np: int,
                         max_np: Optional[int] = None) -> List[SlotInfo]:
    """Assign ranks to host slots, computing local/cross ranks
    (hosts.py:100).  Rank order: fill each host's slots in host order, like
    the reference (rank = host-major), so local ranks are contiguous."""
    if max_np is None:
        max_np = min_np
    # Merge duplicate hostnames additively ("h1:2,h1:2" ≡ "h1:4"), keeping
    # first-seen order — otherwise local/cross rank bookkeeping would emit
    # duplicate (host, local_rank) pairs.
    merged: Dict[str, HostInfo] = {}
    for h in hosts:
        if h.hostname in merged:
            merged[h.hostname] = HostInfo(
                h.hostname, merged[h.hostname].slots + h.slots)
        else:
            merged[h.hostname] = HostInfo(h.hostname, h.slots)
    hosts = list(merged.values())
    total = sum(h.slots for h in hosts)
    if total < min_np:
        raise ValueError(
            f"Requested {min_np} processes but only {total} slots available "
            f"on {[h.hostname for h in hosts]}")
    np_ = min(total, max_np)
    assignments: List[SlotInfo] = []
    rank = 0
    local_sizes: Dict[str, int] = {}
    cross_ranks: Dict[str, int] = {}
    for host_idx, h in enumerate(hosts):
        if rank >= np_:
            break
        use = min(h.slots, np_ - rank)
        cross_ranks[h.hostname] = len(cross_ranks)
        local_sizes[h.hostname] = use
        for local in range(use):
            assignments.append(SlotInfo(
                hostname=h.hostname, rank=rank, local_rank=local,
                cross_rank=cross_ranks[h.hostname],
                size=np_, local_size=use, cross_size=0))
            rank += 1
    n_hosts = len(cross_ranks)
    for a in assignments:
        a.cross_size = n_hosts
    return assignments
