"""Per-task shim for jsrun launches: JSM rank -> rendezvous slot env.

Reference analog: under ``jsrun`` the reference's workers learn their rank
from the MPI runtime the launcher wired up (js_run.py:34 runs one jsrun
covering every rank).  This build has no MPI runtime — workers identify
through HOROVOD_* env the launcher normally injects per spawned process.
jsrun starts every task with the SAME command line, so the launcher wraps
the user command in this shim: it reads the task's global rank from the
JSM/PMIx environment (JSM_NAMESPACE_RANK, falling back to
OMPI_COMM_WORLD_RANK / PMIX_RANK), fetches its SlotInfo record from the
launcher's rendezvous KV (RendezvousServer.init publishes ``rank/{n}``),
exports the standard worker env, and execs the user command.

Usage (constructed by launch.py's jsrun branch):
    jsrun --erf_input <rankfile> python -m horovod_tpu.runner.jsrun_shim \
        <command> [args...]
"""

from __future__ import annotations

import json
import os
import sys
import time

from .. import config as _config
from .hosts import SlotInfo
from .http_server import KVStoreClient

_RANK_VARS = ("JSM_NAMESPACE_RANK", "OMPI_COMM_WORLD_RANK", "PMIX_RANK")
_SIZE_VARS = ("JSM_NAMESPACE_SIZE", "OMPI_COMM_WORLD_SIZE")


def _jsm_rank() -> int:
    for var in _RANK_VARS:
        v = os.environ.get(var)
        if v is not None:
            return int(v)
    raise SystemExit(
        "jsrun_shim: no task rank in the environment (expected one of "
        f"{', '.join(_RANK_VARS)}); was this process started by jsrun?")


def _jsm_size():
    for var in _SIZE_VARS:
        v = os.environ.get(var)
        if v is not None:
            return int(v)
    return None


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        raise SystemExit("jsrun_shim: no command to exec")
    rank = _jsm_rank()
    addr = os.environ[_config.HOROVOD_RENDEZVOUS_ADDR]
    port = int(os.environ[_config.HOROVOD_RENDEZVOUS_PORT])
    client = KVStoreClient(addr, port)
    deadline = time.time() + float(os.environ.get(
        "HVD_TPU_JSRUN_SHIM_TIMEOUT_S", "60"))
    while True:
        raw = client.get("rendezvous", f"rank/{rank}",
                         wait=min(5.0, max(0.1, deadline - time.time())))
        if raw is not None:
            break
        if time.time() >= deadline:
            raise SystemExit(
                f"jsrun_shim: rendezvous at {addr}:{port} never published "
                f"a slot record for rank {rank}")
    slot = SlotInfo.from_dict(json.loads(raw))
    jsm_size = _jsm_size()
    if jsm_size is not None and jsm_size != slot.size:
        # --binding-args started a different task count than the launcher
        # assigned slots for; a size mismatch would hang the collectives
        # at init, so fail fast and name the cause.
        raise SystemExit(
            f"jsrun_shim: jsrun started {jsm_size} tasks but the launcher "
            f"assigned {slot.size} slots — check --binding-args against "
            f"-np/the allocation")
    os.environ.update(slot.env())
    out_dir = os.environ.get("HVD_TPU_OUTPUT_DIR")
    if out_dir:
        # --output-filename's per-rank directory contract (launch.py
        # run_slot): rank.N/stdout|stderr, same shape as the ssh path.
        d = os.path.join(out_dir, f"rank.{slot.rank}")
        os.makedirs(d, exist_ok=True)
        for name, fd in (("stdout", 1), ("stderr", 2)):
            f = open(os.path.join(d, name), "w")
            os.dup2(f.fileno(), fd)
    os.execvp(argv[0], argv)


if __name__ == "__main__":
    main()
