"""Eager (op-by-op) collective dispatch engine.

Reference analog: the enqueue→negotiate→execute pipeline of L3-L5 — per-op
enqueue (EnqueueTensorAllreduce, operations.cc:1408), the HandleManager int
handle → status map of the Torch binding (torch/handle_manager.h:48,
mpi_ops_v2.cc:76), and background execution.  On TPU the execution itself is a
jit-compiled XLA collective over the device mesh; "async" comes for free from
JAX's asynchronous dispatch, so a handle wraps the not-yet-materialized output
arrays and ``synchronize`` is ``block_until_ready`` — no background thread, no
cycle-time tax (the reference itself forces cycle time 0 on its XLA path,
operations.cc:528-534).

Three process modes (horovod_tpu/topology.py):

* **single rank** (size==1, the one-real-chip dev box): Horovod np=1
  semantics — collectives are local transforms (scale/slice only).
* **emulated ranks** (``HVD_TPU_EMULATE_RANKS=N`` over N local devices): eager
  tensors are *stacked* per-rank values of shape ``[N, ...]``; the engine
  shard_maps the axis-level collective over the mesh and returns the stacked
  per-rank results.  This is the hermetic analog of the reference running its
  parallel test suite under ``horovodrun -np N`` on CPU Gloo (SURVEY.md §4).
* **multi-process** (one controller per host): each process contributes its
  local tensor; the engine forms a global array over a one-device-per-process
  submesh and runs the same compiled collective; the result shard comes back
  to the caller.  Issue-order consistency across processes is the negotiation
  contract — enforced by the C++ controller core (csrc/) exactly because
  eager per-rank op order is nondeterministic (controller.cc:74).

Compiled executables are cached per (op, shape, dtype, static params) — the
response-cache analog for the data plane (response_cache.h:45 caches
negotiation results; XLA's compilation cache plays that role here, and the
C++ ResponseCache covers the negotiation side).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collective_ops as C
from ..utils import get_logger


class HandleManager:
    """int handle → result pytree (torch/handle_manager.h:48 analog)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._results: Dict[int, Any] = {}

    def allocate(self, result) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = result
            return h

    def poll(self, handle: int) -> bool:
        """True when the output is materialized (hvd.poll,
        torch/mpi_ops.py poll)."""
        with self._lock:
            res = self._results[handle]
        leaves = jax.tree_util.tree_leaves(res)
        return all(getattr(l, "is_ready", lambda: True)() for l in leaves)

    def wait(self, handle: int):
        """Block and return outputs (hvd.synchronize)."""
        with self._lock:
            if handle not in self._results:
                raise ValueError(f"unknown or already-synchronized handle {handle}")
            res = self._results.pop(handle)
        return jax.block_until_ready(res)


class EagerEngine:
    def __init__(self, mesh: Mesh, axis: str, topology):
        self.mesh = mesh
        self.axis = axis
        self.topo = topology
        self.handles = HandleManager()
        self._exec_cache: Dict[Tuple, Any] = {}
        self._eager_mesh: Optional[Mesh] = None
        self._queue = None       # native TensorQueue (duplicate detection)
        self._negotiator = None  # multi-controller negotiation endpoint

    # -- mode helpers -------------------------------------------------------

    @property
    def n(self) -> int:
        return self.topo.size

    def _multiproc_mesh(self) -> Mesh:
        """One device per process — the controller-plane mesh used to move
        per-process eager tensors (the reference's GLOBAL communicator,
        common.h:176-180)."""
        if self._eager_mesh is None:
            per_proc: Dict[int, Any] = {}
            for d in self.mesh.devices.flat:
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[p] for p in sorted(per_proc)]
            self._eager_mesh = Mesh(np.asarray(devs), (self.axis,))
        return self._eager_mesh

    # -- compiled-callable cache -------------------------------------------

    def _compiled(self, key: Tuple, build):
        fn = self._exec_cache.get(key)
        if fn is None:
            fn = build()
            self._exec_cache[key] = fn
        return fn

    def _stacked_run(self, kind: str, body, tensors: Sequence[jax.Array],
                     static_params: Tuple, mesh: Mesh):
        """shard_map ``body`` over ``mesh`` with stacked [N, ...] inputs and
        stacked [N, ...] outputs; jitted + cached."""
        avals = tuple((t.shape, str(t.dtype)) for t in tensors)
        key = (kind, avals, static_params, id(mesh))

        def build():
            def mapped(*xs):
                def inner(*xs_local):
                    outs = body(*(x[0] for x in xs_local))
                    if not isinstance(outs, (tuple, list)):
                        outs = (outs,)
                    return tuple(o[None] for o in outs)
                return jax.shard_map(
                    inner, mesh=mesh,
                    in_specs=tuple(P(self.axis) for _ in xs),
                    out_specs=P(self.axis))(*xs)
            return jax.jit(mapped)

        return self._compiled(key, build)(*tensors)

    # -- input normalization ------------------------------------------------

    def _as_stacked(self, t: jax.Array, stacked: Optional[bool] = None):
        """Emulated mode input classification.

        ``stacked=True``: the tensor is a per-rank stack [N, ...].
        ``stacked=False``: the tensor is *replicated* (every rank passed the
        same value — the broadcast_variables idiom) and is tiled.
        ``stacked=None``: heuristic — leading dim == N means stacked.  The
        heuristic misfires for a replicated tensor whose first dim happens to
        equal N; callers that know the intent (functions.py helpers) pass the
        flag explicitly.  Returns (stacked_tensor, was_stacked)."""
        t = jnp.asarray(t)
        if stacked is None:
            stacked = t.ndim >= 1 and t.shape[0] == self.n
        if stacked:
            if t.ndim == 0 or t.shape[0] != self.n:
                raise ValueError(
                    f"stacked per-rank tensor must have leading dim "
                    f"{self.n}; got shape {t.shape}")
            return t, True
        return jnp.broadcast_to(t[None], (self.n,) + t.shape), False

    def _to_global(self, t: jax.Array) -> jax.Array:
        """Multi-process mode: local [...] → global stacked [size, ...]."""
        mesh = self._multiproc_mesh()
        t = jnp.asarray(t)
        sharding = NamedSharding(mesh, P(self.axis, *([None] * t.ndim)))
        local = jax.device_put(t[None], self.mesh.local_mesh.devices.flat[0])
        return jax.make_array_from_single_device_arrays(
            (self.n,) + t.shape, sharding, [local])

    def _from_global(self, g: jax.Array) -> jax.Array:
        return g.addressable_data(0)[0]

    # -- generic dispatch ---------------------------------------------------

    def run(self, kind: str, body, tensors: List[jax.Array],
            static_params: Tuple, single_rank_fn,
            name: Optional[str] = None,
            stacked: Optional[bool] = None,
            op_id: int = 0,
            prescale: float = 1.0,
            postscale: float = 1.0,
            ps_id: int = 0,
            ps_ranks=None) -> List[jax.Array]:
        """Dispatch one eager collective; returns per-rank outputs
        (stacked in emulated mode, local otherwise).

        ``name`` reproduces the reference's tensor-name contract: a second
        in-flight collective under the same name raises DuplicateNameError
        (common.h:239), and named ops get timeline lifecycle events."""
        from .. import core as _core
        tl = _core._state.timeline
        if tl is not None:
            # Each eager dispatch is one "cycle" of the runtime
            # (HOROVOD_TIMELINE_MARK_CYCLES, timeline.cc MarkCycle).
            tl.mark_cycle()
        # Unnamed ops get a stable signature-derived label: distinct unnamed
        # collectives must not share one negotiation/cache key (they would
        # alternately invalidate each other), and per-call counters would
        # defeat the response cache across steps.  The reference frameworks
        # auto-name by parameter; shape+dtype fingerprinting is the eager
        # equivalent.
        if name is None:
            fp = "-".join(
                f"{jnp.asarray(t).dtype}x{'x'.join(map(str, jnp.asarray(t).shape))}"
                for t in tensors) if tensors else "none"
            label = f"{kind}.noname.{fp}"
        else:
            label = name
        # Profiler op range (the NVTX bracket of nvtx_op_range.h:65,79):
        # every eager dispatch shows up as one named range in jax.profiler
        # traces, spanning negotiation + execution.  Entered BEFORE the
        # name claim so no exception path can leak a claimed name.
        prof_range = jax.profiler.TraceAnnotation(f"hvd::{kind}::{label}")
        prof_range.__enter__()
        claimed = False
        try:
            self.claim_name(name)
            claimed = True
            if tl is not None:
                tl.negotiate_start(label, kind.upper())
                tl.negotiate_rank_ready(label, self.topo.rank)
                tl.negotiate_end(label, kind.upper())
                tl.start(label, kind.upper())
            try:
                if self.n == 1:
                    return [jnp.asarray(r) for r in single_rank_fn(
                        [jnp.asarray(t) for t in tensors])]
                if self.topo.emulated:
                    pairs = [self._as_stacked(t, stacked) for t in tensors]
                    stacked_ts = [p[0] for p in pairs]
                    if tl is None:
                        outs = self._stacked_run(kind, body, stacked_ts,
                                                 static_params, self.mesh)
                    else:
                        with tl.activity(label, "XLA_EXECUTE"):
                            outs = self._stacked_run(kind, body, stacked_ts,
                                                     static_params, self.mesh)
                    if not isinstance(outs, (tuple, list)):
                        outs = [outs]
                    # Replicated inputs to uniform-output collectives
                    # (allreduce/allgather/broadcast/barrier produce the same
                    # result on every rank) come back unstacked, so idioms
                    # like broadcast_variables(params) round-trip shapes.
                    uniform = kind in ("allreduce", "grouped_allreduce",
                                       "allgather", "allgather_sizes",
                                       "broadcast", "barrier")
                    if uniform and not any(p[1] for p in pairs):
                        return [o[0] for o in outs]
                    return list(outs)
                # Multi-process: negotiate first (coordinator/worker
                # contract, controller.cc:74) so mismatched order/shape
                # fails loudly instead of deadlocking ICI.
                neg = self.negotiator
                if neg.enabled and tensors:
                    # Combined signature over ALL tensors: a mismatch in any
                    # member of a grouped collective must fail validation
                    # (controller.cc:496), not just tensors[0].
                    ts_arr = [jnp.asarray(t) for t in tensors]
                    dtype_sig = ",".join(str(t.dtype) for t in ts_arr)
                    ragged_dim0 = kind.startswith("allgather")
                    shape_sig = []
                    for t in ts_arr:
                        shape_sig.append(t.ndim)
                        dims = list(t.shape)
                        if ragged_dim0 and dims:
                            dims[0] = -1  # allgatherv: dim0 may differ
                        shape_sig.extend(dims)
                    neg.negotiate(label, kind, dtype_sig, tuple(shape_sig),
                                  op_id, prescale=prescale,
                                  postscale=postscale, ps_id=ps_id,
                                  ps_ranks=ps_ranks, timeline=tl)
                mesh = self._multiproc_mesh()
                try:
                    global_ts = [self._to_global(t) for t in tensors]
                    outs = self._stacked_run(kind, body, global_ts,
                                             static_params, mesh)
                    if not isinstance(outs, (tuple, list)):
                        outs = [outs]
                    return [self._from_global(o) for o in outs]
                except Exception as e:
                    # A failed compiled collective (peer died, gloo/ICI
                    # context torn down mid-run) is the reference's
                    # HorovodInternalError contract (exceptions.py:18) —
                    # elastic restores the last commit and re-initializes.
                    # PJRT surfaces these inconsistently — JaxRuntimeError
                    # for most, but a gloo TCP reset arrives as a plain
                    # ValueError("UNKNOWN: Gloo all-reduce failed ...") —
                    # so match on the runtime-failure text, not the type,
                    # and never swallow genuine programming errors.
                    from ..exceptions import HorovodInternalError
                    if isinstance(e, HorovodInternalError):
                        raise
                    msg = str(e)
                    runtime_markers = (
                        "Gloo", "gloo", "UNKNOWN:", "INTERNAL:",
                        "DEADLINE_EXCEEDED", "Connection reset",
                        "Socket closed", "coordination service",
                        "UNAVAILABLE:", "ABORTED:")
                    if isinstance(e, jax.errors.JaxRuntimeError) or \
                            any(m in msg for m in runtime_markers):
                        raise HorovodInternalError(
                            f"collective {label!r} failed on the device "
                            f"runtime: {e}") from e
                    raise
            finally:
                if tl is not None:
                    tl.end(label, kind.upper())
        finally:
            prof_range.__exit__(None, None, None)
            if claimed:
                self.release_name(name)

    # -- native core hooks ----------------------------------------------------

    @property
    def queue(self):
        """Native TensorQueue (tensor_queue.h:28): duplicate in-flight name
        detection in the C++ core."""
        if self._queue is None:
            from ..csrc import NativeTensorQueue
            self._queue = NativeTensorQueue()
        return self._queue

    @property
    def negotiator(self):
        """Multi-controller negotiation endpoint (ops/negotiation.py);
        enabled only in multi-process runs launched with a rendezvous."""
        if self._negotiator is None:
            from .. import core as _core
            from .negotiation import Negotiator
            self._negotiator = Negotiator(self.topo.rank, self.topo.size,
                                          _core._state.config)
        return self._negotiator

    # -- join (JoinOp, collective_operations.h:308) --------------------------

    def join(self) -> int:
        """Signal no-more-data; service peers' collectives with zero
        contributions until every rank has joined; return the id of the last
        rank to join (hvd.join semantics, torch/mpi_ops.py:1293).

        Mechanism: follow live ranks' replayable dispatch streams
        (ops/negotiation.py publish_dispatch) from this rank's own seq
        position, zero-filling every record.  Replays negotiate/publish
        like normal dispatches, so this rank's stream stays seq-aligned
        with its peers' across join rounds.  The coordinator joining needs
        no special path: its replay of a negotiated record coordinates that
        record inline."""
        import time as _time
        if self.n == 1:
            return 0
        if self.topo.emulated or not self.negotiator.enabled:
            # Single-controller emulation: all "ranks" share this process —
            # everyone joins at once.
            return self.n - 1
        neg = self.negotiator
        round_ = neg.join_round
        neg.announce_join(round_)
        deadline = _time.time() + neg._timeout
        while True:
            joined = neg.joined_ranks(round_)  # rank -> {"order","seq"}
            live = [r for r in range(self.n) if r not in joined]
            if not live:
                # Everyone joined; drain up to the highest live-issued seq
                # (a rank may have dispatched collectives and joined before
                # this rank replayed them).
                target = max(m["seq"] for m in joined.values())
                if neg.dispatch_seq >= target:
                    break
                src = max(joined, key=lambda r: joined[r]["seq"])
            else:
                src = live[0]
            rec = neg.poll_dispatch(src, neg.dispatch_seq + 1)
            if rec is not None and live:
                # Stale-snapshot guard: ``joined`` was read BEFORE the
                # poll, so ``src`` may have joined meanwhile and this
                # record may be its first NEXT-round dispatch — replaying
                # it would zero a live rank's contribution one round later
                # (observed as a wrong sum under full-suite load).  The
                # join marker is published synchronously before any
                # next-round record can reach the stream (announce_join is
                # a direct put; records ride the batched flusher), so a
                # fresh marker read is authoritative: past its seq, stop —
                # the all-joined drain branch caps the replay at target.
                m = neg.join_marker(round_, src)
                if m is not None and rec["seq"] > m["seq"]:
                    continue
            if rec is not None:
                self._replay_record(rec)
                # The replay published; neg.dispatch_seq advanced by one.
                deadline = _time.time() + neg._timeout
                continue
            if _time.time() > deadline:
                from ..exceptions import HorovodInternalError
                raise HorovodInternalError(
                    f"join timed out; joined={sorted(joined)} of {self.n}")
            _time.sleep(0.005)
        last = max(joined, key=lambda r: (joined[r]["order"], r))
        neg.finish_join_round(round_, last)
        neg.join_round += 1
        return last

    def _replay_record(self, rec: dict) -> None:
        """Contribute zeros to a peer's collective (joined-ranks-contribute-
        zeros, JoinOp semantics).  The signature encodes everything needed to
        reconstruct the call (KIND_IDS folding, ops/negotiation.py).

        Every path through here MUST advance this rank's dispatch_seq by
        exactly one (the replayed dispatch publishes its own stream record);
        a record that cannot be replayed is fatal — skipping it would stall
        the stream and hang the live ranks inside the collective."""
        from .. import core as _core
        from .. import ops as _pub
        from ..exceptions import HorovodInternalError
        sig, kind, name = rec["sig"], rec["kind"], rec["name"]
        dtypes = sig["dtype"].split(",")
        dims = sig["shape"]
        shapes, i = [], 0
        for _ in dtypes:
            nd = dims[i]
            i += 1
            shapes.append(tuple(dims[i:i + nd]))
            i += nd
        if kind.startswith("allgather"):
            # Allgather-family records replay the RAW inner dispatches of
            # _allgatherv_parts one-to-one (re-entering the public
            # hvd.allgather would nest a fresh size exchange no live rank
            # ever issues and deadlock — the ragged path is two dispatches,
            # each with its own stream record).
            self._replay_allgather_record(rec, kind, name, dtypes, shapes)
            return
        if any(d < 0 for s in shapes for d in s):
            raise HorovodInternalError(
                f"join: cannot zero-fill collective {name!r} "
                f"(non-concrete shape in replay record)")
        if rec["epoch"] < self.negotiator._epochs.get(name, 0):
            # Streams replay only records issued after this rank's own seq,
            # which it never participated in — an older epoch here means the
            # stream and epoch bookkeeping disagree.
            raise HorovodInternalError(
                f"join: replay record for {name!r} has epoch "
                f"{rec['epoch']} < local {self.negotiator._epochs.get(name)}")
        zeros = [jnp.zeros(s, dtype=jnp.dtype(dt))
                 for s, dt in zip(shapes, dtypes)]
        # Align the local epoch counter with the negotiated epoch.
        self.negotiator._epochs[name] = rec["epoch"]
        op_id = sig["op"]
        pre, post = sig.get("prescale", 1.0), sig.get("postscale", 1.0)
        ps = self._resolve_replay_ps(sig)
        if kind not in ("allreduce", "grouped_allreduce", "broadcast",
                        "reducescatter", "alltoall", "barrier"):
            raise HorovodInternalError(
                f"join: unsupported kind {kind!r} in replay record "
                f"for {name!r}")
        seq_before = self.negotiator.dispatch_seq
        try:
            if kind == "allreduce":
                _pub.allreduce(zeros[0], op=_pub.ReduceOp(op_id), name=name,
                               prescale_factor=pre, postscale_factor=post,
                               process_set=ps)
            elif kind == "grouped_allreduce":
                _pub.grouped_allreduce(zeros, op=_pub.ReduceOp(op_id - 600),
                                       name=name, prescale_factor=pre,
                                       postscale_factor=post, process_set=ps)
            elif kind == "broadcast":
                root = op_id - 10000
                if root == self.topo.rank:
                    # A joined root has no data; zeros would be silently
                    # wrong.  Negotiated dispatches get an error verdict
                    # from the coordinator; for the cached path, poison the
                    # cache so the NEXT dispatch renegotiates and errors.
                    get_logger().error(
                        "broadcast %s has joined rank %d as root; receivers "
                        "get zeros this once and an error on the next "
                        "dispatch", name, root)
                    self.negotiator.cache.invalidate(name)
                    self.negotiator._publish_invalidation(name)
                _pub.broadcast(zeros[0], root_rank=root, name=name,
                               process_set=ps)
            elif kind == "reducescatter":
                _pub.reducescatter(zeros[0], op=_pub.ReduceOp(op_id - 400),
                                   name=name, process_set=ps)
            elif kind == "alltoall":
                _pub.alltoall(zeros[0], name=name, process_set=ps)
            elif kind == "barrier":
                _pub.barrier()
        except HorovodInternalError as e:
            from ..exceptions import CollectiveRejectedError
            if self.negotiator.dispatch_seq == seq_before or \
                    not isinstance(e, CollectiveRejectedError):
                # Nothing was published, or a LOCAL failure (e.g. verdict
                # timeout) that is not symmetric across ranks — live ranks
                # may be inside the device collective expecting our zeros,
                # so continuing to service would hang them silently.
                raise
            # A coordinator rejection (e.g. joined broadcast root) raised
            # on every rank symmetrically AFTER the stream record was
            # published — streams stay aligned, so servicing can continue.
            get_logger().warning("join: replayed %s was rejected: %s",
                                 name, e)

    def _resolve_replay_ps(self, sig: dict):
        """Resolve the process set of a replayed dispatch from its WIRE
        membership (sig['ps_ranks'], see ops._wire_ps) — never from a local
        id, which depends on per-rank registration order.  A joined rank
        that never registered the set auto-registers it here (register()
        dedups against an existing identical set), so join + subset
        collectives reconcile without any registration-order contract."""
        from .. import core as _core
        from ..process_sets import ProcessSet
        ranks = sig.get("ps_ranks")
        if not ranks:
            return _core._require_init().process_set_table.global_set
        return _core._require_init().process_set_table.register(
            ProcessSet(ranks))

    def _replay_allgather_record(self, rec: dict, kind: str, name: str,
                                 dtypes, shapes) -> None:
        """Zero-contribute to a live ranks' ragged allgather.

        _allgatherv_multiproc (ops/__init__.py) issues exactly two raw
        dispatches — a fixed-shape dim0-size exchange ("allgather_sizes",
        [1] int64) then a pad-to-max gather ("allgather", [max_rows, ...]).
        Each produces its own joinop record; this replays the matching raw
        dispatch via eng.run under the recorded label/epoch, contributing 0
        rows: value 0 in the size exchange, and an all-zero [max_rows, ...]
        buffer in the main gather (sliced out by live ranks, since our
        announced size is 0 — the reference's empty-slice join semantics,
        torch JoinOp + allgather).  max_rows is recovered from the size
        exchange this rank just serviced (the records of one public
        allgather are adjacent: live ranks block on the size exchange
        before negotiating the main gather)."""
        from jax import lax as _lax
        from . import collective_ops as _C
        from ..exceptions import HorovodInternalError
        # Consume the size-exchange pairing slot the moment a main-gather
        # record arrives so a later allgather can never pair with a stale
        # sizes vector.
        sizes = None
        if kind == "allgather":
            sizes = getattr(self, "_join_gather_sizes", None)
            self._join_gather_sizes = None
        if rec["epoch"] < self.negotiator._epochs.get(name, 0):
            raise HorovodInternalError(
                f"join: replay record for {name!r} has epoch "
                f"{rec['epoch']} < local {self.negotiator._epochs.get(name)}")
        axis = self.axis
        self.negotiator._epochs[name] = rec["epoch"]
        if kind == "allgather_sizes":
            zero = jnp.zeros((1,), jnp.dtype(dtypes[0]))

            def size_body(x):
                return _C.allgather(x, axis_name=axis)

            sizes = self.run("allgather_sizes", size_body, [zero], (),
                             lambda ts: ts, name=name)[0]
            self._join_gather_sizes = np.asarray(sizes).ravel()
            return
        # Main gather: dim0 was published as the ragged marker (-1); the
        # true padded extent is max over the announced sizes.
        if sizes is None or sizes.size == 0:
            raise HorovodInternalError(
                f"join: allgather record {name!r} arrived without a "
                f"preceding size exchange (stream order violation)")
        max_rows = int(sizes.max())
        trailing = tuple(d for d in shapes[0][1:])
        if any(d < 0 for d in trailing):
            raise HorovodInternalError(
                f"join: cannot reconstruct trailing dims for {name!r}")
        zero = jnp.zeros((max_rows,) + trailing, jnp.dtype(dtypes[0]))

        def body(x):
            return _lax.all_gather(x, axis, axis=0)

        self.run("allgather", body, [zero], (max_rows,),
                 lambda ts: [ts[0][None]], name=name)

    def claim_name(self, name: Optional[str]):
        if name is None:
            return None
        from ..exceptions import DuplicateNameError
        if not self.queue.add(name, "", []):
            raise DuplicateNameError(
                f"collective named {name!r} already in flight "
                f"(reference: DUPLICATE_NAME_ERROR, common.h:239)")
        return name

    def release_name(self, name: Optional[str]):
        if name is not None:
            self.queue.finish(name)
