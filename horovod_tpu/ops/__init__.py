"""Public collective-op API — the Horovod op surface on TPU.

Mirrors horovod/torch/mpi_ops.py:110-1315 and tensorflow/mpi_ops.py: sync +
``_async`` + in-place variants of allreduce/allgather/broadcast/alltoall/
reducescatter, grouped variants, ``poll``/``synchronize``, ``barrier`` and
``join``.  (JAX arrays are immutable, so the in-place spellings — kept for API
compatibility — return new arrays; the reference's in-place forms exist to
avoid output allocation, which XLA handles via buffer donation instead.)

Dispatch: when called inside a jit/shard_map trace where the framework mesh
axis is bound, these lower *directly* to the axis-level primitives in
``collective_ops`` (the compiled data plane — no runtime hop at all, the
reference's HOROVOD_ENABLE_XLA_OPS path done natively, SURVEY.md §3.5).
Called eagerly, they dispatch through ops/eager.py over the device mesh.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .collective_ops import (  # noqa: F401
    ReduceOp, Average, Sum, Adasum, Min, Max, Product,
    reducescatter_padded_size,
)
from . import collective_ops as C
from .. import core as _core
from ..compression import Compression
from ..process_sets import ProcessSet, global_process_set


def _axis() -> str:
    if _core.is_initialized():
        return _core._state.config.mesh_axis
    return "hvd"


def _axis_bound(axis_name: str) -> bool:
    """True when a mesh axis of that name is bound (inside shard_map/pmap) —
    the dispatch switch between the compiled and eager paths."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def _engine():
    st = _core._require_init()
    if st.eager_engine is None:
        from .eager import EagerEngine
        st.eager_engine = EagerEngine(st.mesh, st.config.mesh_axis, st.topology)
    return st.eager_engine


def _members(process_set: Optional[ProcessSet]):
    if process_set is None or process_set.ranks is None:
        return None
    return process_set.members()


def _wire_ps(process_set: Optional[ProcessSet]) -> dict:
    """Canonical wire identity of a process set for negotiation signatures.

    The LOCAL process_set_id depends on per-rank registration order, so it
    must never cross the wire: two ranks that registered the same sets in a
    different order would fail validation on a perfectly matched collective,
    and a joined rank could replay a record against the wrong set.  Instead
    the wire carries (a) a membership-derived 31-bit id (FNV-1a over the
    sorted ranks — order-independent, feeds the native cache/message table)
    and (b) the member ranks themselves, from which a replaying rank
    resolves — or auto-registers — the matching local set.  Reference
    semantics: process-set ids are agreed collectively at registration
    (operations.cc:1262); here the membership IS the agreement."""
    members = _members(process_set)
    if members is None:
        return {"ps_id": 0, "ps_ranks": None}
    h = 0x811C9DC5
    for r in members:
        h = ((h ^ (r + 1)) * 0x01000193) & 0x7FFFFFFF
    return {"ps_id": h or 1, "ps_ranks": list(members)}


def _normalize_op(op, average):
    """Resolve the deprecated ``average`` flag vs ``op``
    (torch/mpi_ops.py:110-150 handle_average_backwards_compatibility)."""
    if average is not None:
        if op is not None:
            raise ValueError("The op parameter supersedes average; "
                             "please provide only one of them")
        warnings.warn("average is deprecated, use op=hvd.Average or "
                      "op=hvd.Sum instead", DeprecationWarning, stacklevel=3)
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    return ReduceOp.AVERAGE if op is None else ReduceOp(op)


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce(tensor,
              average=None,
              name: Optional[str] = None,
              compression=Compression.none,
              op=None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              process_set: ProcessSet = global_process_set):
    """Allreduce (hvd.allreduce; torch/mpi_ops.py:335, tensorflow mpi_ops).

    In-trace (axis bound): lowers to a lax collective inline.
    Eager: dispatches via the engine; see ops/eager.py mode semantics.
    """
    rop = _normalize_op(op, average)
    axis = _axis()
    members = _members(process_set)
    tensor, ctx = compression.compress(tensor)
    if _axis_bound(axis):
        # HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE
        # (nccl_operations.h:231, :253) are accepted and map to the flat
        # lax.psum: on TPU, XLA already lowers psum with torus-native
        # hierarchical decomposition, which is precisely what the
        # reference's software torus approximates (SURVEY.md §7).  Routing
        # through the explicit two-phase form here would also change the
        # result's vma type (grouped collectives yield varying outputs) and
        # break replicated out_specs that plain psum satisfies.  The
        # explicit form stays available for 2-D mesh experts as
        # collective_ops.hierarchical_allreduce.
        out = C.allreduce(tensor, rop, axis_name=axis, members=members,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor)
        return compression.decompress(out, ctx)

    eng = _engine()

    def body(x):
        return C.allreduce(x, rop, axis_name=axis, members=members,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)

    def single(ts):
        # np=1: every ReduceOp reduces a single operand to itself; only the
        # scale factors apply (rop was validated by _normalize_op).
        x = C._apply_scale(ts[0], prescale_factor)
        return [C._apply_scale(x, postscale_factor)]

    out = eng.run("allreduce",
                  body, [tensor],
                  (int(rop), members, prescale_factor, postscale_factor),
                  single, name=name, op_id=int(rop),
                  prescale=prescale_factor, postscale=postscale_factor,
                  **_wire_ps(process_set))[0]
    return compression.decompress(out, ctx)


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: ProcessSet = global_process_set) -> int:
    """Async allreduce → handle (torch/mpi_ops.py:260 allreduce_async_).
    JAX dispatch is already asynchronous; the handle wraps the future
    output arrays."""
    out = allreduce(tensor, average=average, name=name, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    process_set=process_set)
    return _engine().handles.allocate(out)


# In-place spellings kept for API parity (JAX arrays are immutable; XLA
# buffer donation provides the memory win the reference's in-place ops target).
allreduce_ = allreduce
allreduce_async_ = allreduce_async


def grouped_allreduce(tensors: Sequence,
                      average=None,
                      name=None,
                      compression=Compression.none,
                      op=None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      process_set: ProcessSet = global_process_set) -> List:
    """Grouped allreduce: all-or-nothing readiness (GroupTable,
    group_table.h:31; torch/mpi_ops.py grouped_allreduce)."""
    rop = _normalize_op(op, average)
    axis = _axis()
    members = _members(process_set)
    compressed = [compression.compress(t) for t in tensors]
    ts = [c[0] for c in compressed]
    ctxs = [c[1] for c in compressed]
    if _axis_bound(axis):
        outs = C.grouped_allreduce(ts, rop, axis_name=axis, members=members,
                                   prescale_factor=prescale_factor,
                                   postscale_factor=postscale_factor)
    else:
        eng = _engine()

        def body(*xs):
            return tuple(C.grouped_allreduce(
                list(xs), rop, axis_name=axis, members=members,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor))

        def single(xs):
            return [C._apply_scale(C._apply_scale(x, prescale_factor),
                                   postscale_factor) for x in xs]

        outs = eng.run("grouped_allreduce", body, list(ts),
                       (int(rop), members, prescale_factor, postscale_factor),
                       single, name=name, op_id=int(rop),
                       prescale=prescale_factor, postscale=postscale_factor,
                       **_wire_ps(process_set))
    return [compression.decompress(o, c) for o, c in zip(outs, ctxs)]


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: ProcessSet = global_process_set) -> int:
    outs = grouped_allreduce(tensors, average=average, name=name, op=op,
                             prescale_factor=prescale_factor,
                             postscale_factor=postscale_factor,
                             process_set=process_set)
    return _engine().handles.allocate(outs)


grouped_allreduce_ = grouped_allreduce
grouped_allreduce_async_ = grouped_allreduce_async


def _fusion_pack(*ts):
    """Device-side pack: one concatenate instead of a device→host copy
    per tensor (the reference engineered the same away with batched D2D
    memcpy kernels, cuda_kernels.h:32-46).  Deliberately EAGER, not
    jitted: autotune shifts fusion thresholds across scoring windows, so
    bucket compositions change and a jitted pack would recompile on the
    very steps autotune is timing; eager dispatch is a handful of cheap
    reshape views plus one concatenate op."""
    return jnp.concatenate([t.ravel() for t in ts])


def _fused_allreduce(tensors: Sequence, op,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     compression=Compression.none,
                     process_set: ProcessSet = global_process_set) -> List:
    """Eager fused allreduce over one FLAT fusion buffer: device-side pack
    (MemcpyInFusionBuffer, operations.cc:519 — here an eager device-side
    concatenate, see _fusion_pack, so gradients stay device-resident
    instead of round-tripping through host numpy), a single dispatched
    collective for the whole bucket,
    then device-side slice+reshape (MemcpyOutFusionBuffer).  One global-
    array assembly instead of one per tensor — the reference's tensor-
    fusion data path, which is where the eager dispatch time went.

    ``compression`` (fp16/bf16) is applied ONCE to the packed buffer —
    the planner's buckets are same-dtype, and a cast is elementwise, so
    compress(concat(ts)) == concat(compress(t) for ts) and the per-tensor
    grouped path's numerics are preserved with one cast + one collective
    per bucket instead of one pair per tensor (docs/tensor_fusion.md).

    All tensors must share one dtype (the fusion planner only buckets
    same-dtype entries, csrc PlanFusion / controller.cc:901)."""
    rop = ReduceOp(op)
    axis = _axis()
    members = _members(process_set)
    eng = _engine()
    ts = [jnp.asarray(t) for t in tensors]
    shapes = [t.shape for t in ts]
    sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    flat, cctx = compression.compress(_fusion_pack(*ts))

    def body(x):
        return C.allreduce(x, rop, axis_name=axis, members=members,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor)

    def single(ts):
        x = C._apply_scale(ts[0], prescale_factor)
        return [C._apply_scale(x, postscale_factor)]

    out = eng.run("allreduce", body, [flat],
                  (int(rop), members, prescale_factor, postscale_factor),
                  single, name=f"fusedbuf.{flat.dtype}.{int(offsets[-1])}",
                  op_id=int(rop), prescale=prescale_factor,
                  postscale=postscale_factor,
                  **_wire_ps(process_set))[0]
    out = compression.decompress(out, cctx)  # ctx = pre-wire flat dtype
    return [out[int(a):int(b)].reshape(s)
            for a, b, s in zip(offsets[:-1], offsets[1:], shapes)]


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------

def allgather(tensor, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set):
    """Concatenate every participant's tensor along axis 0 (hvd.allgather,
    torch/mpi_ops.py:700).

    Under jit all participants must pass equal shapes.  Eagerly, ragged dim0
    (allgatherv, MPI_Allgatherv analog) is supported: in emulated mode pass a
    *list* of per-rank tensors; in multi-process mode ragged local dim0 is
    handled via a size exchange + pad-to-max + slice (the reference controller
    gathers recvcounts the same way, collective_operations.h:126).

    HOROVOD_HIERARCHICAL_ALLGATHER (MPIHierarchicalAllgather,
    mpi_operations.cc) is accepted and maps to the flat lax.all_gather —
    XLA lowers it with the torus-native hierarchical decomposition the
    reference's node-leader gather approximates in software."""
    axis = _axis()
    members = _members(process_set)
    if _axis_bound(axis):
        return C.allgather(tensor, axis_name=axis, members=members)
    eng = _engine()
    if isinstance(tensor, (list, tuple)) and eng.topo.emulated:
        return _allgatherv_emulated(list(tensor), members)
    if not eng.topo.emulated and eng.n > 1:
        return _allgatherv_multiproc(tensor, members, name)

    def body(x):
        return C.allgather(x, axis_name=axis, members=members)

    def single(ts):
        return [ts[0]]

    return eng.run("allgather", body, [tensor], (members,), single,
                   name=name,
                   **_wire_ps(process_set))[0]


def _allgatherv_emulated(tensors: List, members) -> List:
    """Ragged allgather, emulated mode: list of per-rank tensors in, list of
    per-rank gathered results out (all equal: the member concat)."""
    eng = _engine()
    n = eng.n
    if len(tensors) != n:
        raise ValueError(
            f"emulated allgatherv takes one tensor per rank ({n}); got "
            f"{len(tensors)}")
    sel = range(n) if members is None else members
    gathered = jnp.concatenate([jnp.asarray(tensors[r]) for r in sel], axis=0)
    return [gathered if members is None or r in set(sel) else
            jnp.asarray(tensors[r]) for r in range(n)]


def _allgatherv_parts(tensor, name):
    """Raw ragged gather: exchange dim0 sizes (fixed shape), pad to max,
    gather, slice per rank — the static-shape-safe allgatherv
    (SURVEY.md §7 "dynamic shapes").  Returns (per-rank blocks, sizes);
    a joined rank's block is empty (its size announcement is 0).

    The two dispatches here are mirrored one-to-one by the join replay
    (ops/eager.py _replay_allgather_record) — change them together."""
    eng = _engine()
    n = eng.n
    t = jnp.asarray(tensor)
    rows = int(t.shape[0])
    size_vec = jnp.asarray(np.array([rows], np.int64))

    def size_body(x):
        return C.allgather(x, axis_name=_axis())

    # The size vector is the one legitimate host sync: the announced row
    # counts determine SHAPES (the reference's recvcounts gather does the
    # same).  The DATA stays device-resident: device-side pad, gather,
    # and per-rank slices — no host round-trip of the payload.
    sizes = np.asarray(eng.run("allgather_sizes", size_body, [size_vec],
                               (), lambda ts: ts, name=None)[0]).ravel()
    max_rows = int(sizes.max())
    if max_rows > rows:
        pad = ((0, max_rows - rows),) + ((0, 0),) * (t.ndim - 1)
        padded = jnp.pad(t, pad)
    else:
        padded = t

    def body(x):
        return lax.all_gather(x, _axis(), axis=0)  # [n, max, ...]

    gathered = eng.run("allgather", body,
                       [padded], (max_rows,),
                       lambda ts: [ts[0][None]], name=name)[0]
    return [gathered[r, :int(sizes[r])] for r in range(n)], sizes


def _allgatherv_multiproc(tensor, members, name):
    """Ragged allgather, multi-process: member blocks concatenated."""
    eng = _engine()
    n = eng.n
    if members is not None and _core.rank() not in set(members):
        # Non-members still participate in the global exchange (the run is
        # SPMD-total over all processes) but keep their input.
        _allgatherv_parts(tensor, name)
        return jnp.asarray(tensor)
    blocks, _ = _allgatherv_parts(tensor, name)
    sel = range(n) if members is None else members
    return jnp.concatenate([blocks[r] for r in sel], axis=0)


def allgather_async(tensor, name=None,
                    process_set: ProcessSet = global_process_set) -> int:
    out = allgather(tensor, name=name, process_set=process_set)
    return _engine().handles.allocate(out)


def grouped_allgather(tensors, name=None,
                      process_set: ProcessSet = global_process_set) -> List:
    return [allgather(t, name=name, process_set=process_set) for t in tensors]


def grouped_allgather_async(tensors, name=None,
                            process_set: ProcessSet = global_process_set) -> int:
    outs = grouped_allgather(tensors, name=name, process_set=process_set)
    return _engine().handles.allocate(outs)


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------

def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set: ProcessSet = global_process_set,
              stacked: Optional[bool] = None):
    """Root's tensor to all participants (hvd.broadcast,
    torch/mpi_ops.py:914).

    ``stacked`` (TPU-build extension, emulated mode only): declare whether
    the tensor is a per-rank stack [N, ...] (True) or a replicated value
    (False); None uses the leading-dim heuristic (see ops/eager.py)."""
    axis = _axis()
    members = _members(process_set)
    if _axis_bound(axis):
        return C.broadcast(tensor, root_rank, axis_name=axis, members=members)
    eng = _engine()

    def body(x):
        return C.broadcast(x, root_rank, axis_name=axis, members=members)

    def single(ts):
        return [ts[0]]

    return eng.run("broadcast", body, [tensor], (root_rank, members),
                   single, name=name, stacked=stacked,
                   op_id=int(root_rank),
                   **_wire_ps(process_set))[0]


def broadcast_async(tensor, root_rank: int = 0, name=None,
                    process_set: ProcessSet = global_process_set) -> int:
    out = broadcast(tensor, root_rank=root_rank, name=name,
                    process_set=process_set)
    return _engine().handles.allocate(out)


broadcast_ = broadcast
broadcast_async_ = broadcast_async


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set: ProcessSet = global_process_set):
    """All-to-all row exchange (hvd.alltoall, torch/mpi_ops.py:1063;
    AlltoallOp PrepareOutputAndParams collective_operations.h:199-268).

    Without ``splits``: equal blocks (dim0 divisible by participants).
    With ``splits`` (len-N int vector: rows I send to each participant):
    returns ``(output, received_splits)`` like the reference.  Ragged exchange
    is an eager-only feature — XLA programs need static shapes."""
    axis = _axis()
    members = _members(process_set)
    if splits is None:
        if _axis_bound(axis):
            return C.alltoall(tensor, axis_name=axis, members=members)
        eng = _engine()

        def body(x):
            return C.alltoall(x, axis_name=axis, members=members)

        def single(ts):
            return [ts[0]]

        return eng.run("alltoall", body, [tensor], (members,), single,
                       name=name,
                       **_wire_ps(process_set))[0]

    if _axis_bound(axis):
        raise ValueError(
            "alltoall with uneven splits requires eager mode: XLA compiled "
            "programs need static shapes (SURVEY.md §7 dynamic shapes)")
    return _alltoallv_eager(tensor, splits, members)


def _alltoallv_eager(tensor, splits, members):
    """Ragged alltoall on the eager path (alltoallv; the controller alltoalls
    the split vectors then sizes the output, collective_operations.h:199-268).

    Emulated mode: ``tensor`` is a list of per-rank tensors (ragged stacks
    can't be one array) and ``splits`` is [N, N]; returns (list of outputs,
    received_splits [N, N]).  Single rank: identity."""
    eng = _engine()
    n = eng.n
    if n == 1:
        return jnp.asarray(tensor), jnp.asarray(splits)
    if eng.topo.emulated:
        tensors = [np.asarray(t) for t in tensor]
        sp = np.asarray(splits).reshape(n, n)
        offsets = np.concatenate(
            [np.zeros((n, 1), np.int64), np.cumsum(sp, axis=1)], axis=1)
        outputs = []
        for recv in range(n):
            parts = [tensors[src][offsets[src, recv]:offsets[src, recv + 1]]
                     for src in range(n)]
            outputs.append(jnp.asarray(np.concatenate(parts, axis=0)))
        received = jnp.asarray(sp.T.copy())
        return outputs, received
    # Multi-process ragged path: gather splits, gather ragged data blocks,
    # then slice received sub-blocks host-side.  A joined rank contributes
    # an EMPTY block to both gathers (ops/eager.py join replay) — its splits
    # row stays all-zero, i.e. it sends nothing to anyone.
    sp_local = np.asarray(splits, dtype=np.int64)
    sp_blocks, sp_sizes = _allgatherv_parts(jnp.asarray(sp_local)[None, :],
                                            None)
    all_splits = np.zeros((n, n), np.int64)
    # ONE device→host sync for the whole split table (it is pure shape
    # metadata): per-block np.asarray would cost n tiny blocking copies.
    present = [src for src in range(n) if sp_sizes[src]]
    if present:
        flat_sp = np.asarray(jnp.concatenate(
            [sp_blocks[src].reshape(-1) for src in present]))
        if flat_sp.size != len(present) * n:
            # A malformed announcement (e.g. the emulated-mode [N, N]
            # splits form passed in multi-process mode) must fail loudly:
            # fixed-stride chunking over a wrong-length vector would
            # silently shift every later rank's row.
            raise ValueError(
                f"alltoall splits exchange returned {flat_sp.size} values "
                f"for {len(present)} ranks (expected {n} per rank); some "
                f"rank announced a malformed splits vector")
        for i, src in enumerate(present):
            all_splits[src] = flat_sp[i * n:(i + 1) * n]
    t = jnp.asarray(tensor)
    data_blocks, _ = _allgatherv_parts(t, None)
    rank = _core.rank()
    offsets = np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(all_splits, axis=1)], axis=1)
    # Device-side sub-block slices + one concatenate: the split table is
    # host metadata (it determines shapes), the payload never leaves the
    # device.
    parts = [data_blocks[src][int(offsets[src, rank]):
                              int(offsets[src, rank + 1])]
             for src in range(n)]
    out = jnp.concatenate(parts, axis=0) if parts else \
        jnp.zeros((0,) + t.shape[1:], t.dtype)
    return out, jnp.asarray(all_splits[:, rank].copy())


def alltoall_async(tensor, splits=None, name=None,
                   process_set: ProcessSet = global_process_set) -> int:
    out = alltoall(tensor, splits=splits, name=name, process_set=process_set)
    return _engine().handles.allocate(out)


# ---------------------------------------------------------------------------
# reducescatter
# ---------------------------------------------------------------------------

def reducescatter(tensor, op=ReduceOp.SUM, name: Optional[str] = None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0,
                  process_set: ProcessSet = global_process_set):
    """Reduce + scatter row blocks (hvd.reducescatter, torch/mpi_ops.py:1203).

    Deviation: uneven dim0 is zero-padded to a multiple of the participant
    count (SPMD uniform shards) instead of the reference's first-ranks-get-
    extra-rows split; ``reducescatter_padded_size`` exposes the padding."""
    rop = ReduceOp(op) if op is not None else ReduceOp.SUM
    axis = _axis()
    members = _members(process_set)
    if _axis_bound(axis):
        return C.reducescatter(tensor, rop, axis_name=axis, members=members,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)
    eng = _engine()

    def body(x):
        return C.reducescatter(x, rop, axis_name=axis, members=members,
                               prescale_factor=prescale_factor,
                               postscale_factor=postscale_factor)

    def single(ts):
        x = C._apply_scale(ts[0], prescale_factor)
        return [C._apply_scale(x, postscale_factor)]

    return eng.run("reducescatter", body, [tensor],
                   (int(rop), members, prescale_factor, postscale_factor),
                   single, name=name, op_id=int(rop),
                   prescale=prescale_factor, postscale=postscale_factor,
                   **_wire_ps(process_set))[0]


def reducescatter_async(tensor, op=ReduceOp.SUM, name=None,
                        process_set: ProcessSet = global_process_set) -> int:
    out = reducescatter(tensor, op=op, name=name, process_set=process_set)
    return _engine().handles.allocate(out)


def grouped_reducescatter(tensors, op=ReduceOp.SUM, name=None,
                          process_set: ProcessSet = global_process_set) -> List:
    return [reducescatter(t, op=op, name=name, process_set=process_set)
            for t in tensors]


def grouped_reducescatter_async(tensors, op=ReduceOp.SUM, name=None,
                                process_set: ProcessSet = global_process_set) -> int:
    outs = grouped_reducescatter(tensors, op=op, name=name,
                                 process_set=process_set)
    return _engine().handles.allocate(outs)


# ---------------------------------------------------------------------------
# handles / synchronization / barrier / join
# ---------------------------------------------------------------------------

def poll(handle: int) -> bool:
    """True when the async op's outputs are materialized (hvd.poll,
    torch/mpi_ops.py:1251)."""
    return _engine().handles.poll(handle)


def synchronize(handle: int):
    """Block until the async op completes and return its output(s)
    (hvd.synchronize, torch/mpi_ops.py:1265)."""
    return _engine().handles.wait(handle)


def barrier(process_set: ProcessSet = global_process_set) -> None:
    """Blocking barrier over the set (hvd.barrier, torch/mpi_ops.py:1315;
    BarrierOp collective_operations.h:335)."""
    axis = _axis()
    if _axis_bound(axis):
        C.barrier(axis_name=axis)
        return
    eng = _engine()
    if eng.n == 1:
        return

    def body(x):
        return x + C.barrier(axis_name=axis)

    token = jnp.zeros((eng.n, 1), jnp.int32) if eng.topo.emulated else \
        jnp.zeros((1,), jnp.int32)
    out = eng.run("barrier", body, [token], (), lambda ts: ts)[0]
    jax.block_until_ready(out)


def join(device: int = -1) -> int:
    """Signal this rank has no more data (hvd.join, torch/mpi_ops.py:1293;
    JoinOp collective_operations.h:308): blocks until every rank joined,
    contributing ZEROS to collectives the surviving ranks keep issuing
    (uneven-data semantics), and returns the id of the last rank to join.

    ``device`` is accepted for API parity (the reference pins the zero
    buffers to a GPU; XLA manages placement here).  Under SPMD jit, uneven
    per-rank step counts cannot occur inside one compiled program — join is
    an eager/multi-controller feature."""
    del device
    return _engine().join()
