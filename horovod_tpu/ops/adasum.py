"""Adasum adaptive-summation allreduce, TPU-native.

The reference implements Adasum as a recursive vector-halving /
distance-doubling template over MPI point-to-point sends
(horovod/common/ops/adasum/adasum.h:38,73,230-344): at each level, paired
ranks exchange half-buffers, compute the dot product and squared norms of the
two halves, allreduce those three scalars over the level's reduction
communicator, and combine ``a' = acoeff*a + bcoeff*b`` with

    acoeff = 1 - dot / (2*||a||^2)
    bcoeff = 1 - dot / (2*||b||^2)          (adasum.h:396-409)

— an orthogonal-projection-corrected sum that behaves like a sum for
orthogonal gradients and like an average for parallel ones.

TPU-native formulation: the same *binary reduction tree* expressed as
``log2(n)`` rounds of ``lax.ppermute`` butterfly exchanges inside the compiled
program.  Each round, rank i exchanges its full working vector with partner
``i XOR 2^level`` and both compute the identical combined vector, so after the
last round every rank holds the tree-reduction result — the allgather "reverse
phase" of the reference (adasum.h:405-412) is unnecessary.  This trades the
reference's halved bandwidth for zero extra latency rounds; on ICI the
butterfly neighbors are physical torus neighbors, which is what
``ppermute`` lowers to natively.

Numerics: dot/norm accumulation runs in float32 islands regardless of input
dtype, the bf16-world analog of the reference computing them in double
(adasum.h:357-363).  ``HVD_ADASUM_ACC_DTYPE=f64`` widens the islands to the
reference's actual double precision (requires jax x64; requesting f64
without it warns and keeps f32 rather than silently computing f32 under an
f64 label).  The knob is read at TRACE time — programs compiled before a
change keep their dtype.  Validated against a NumPy model of the reference
recursion in tests/test_adasum.py (mirrors test/parallel/test_adasum_*.py).

Non-power-of-two participant counts fall back to an all_gather + local tree
with zero-padded virtual ranks (``adasum(a, 0) = a``), preserving the math.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import get_logger

_warned_no_x64 = False


def _acc_dtype():
    """Accumulation dtype for the dot/norm islands (module docstring:
    HVD_ADASUM_ACC_DTYPE, default f32, reference uses f64)."""
    global _warned_no_x64
    name = os.environ.get("HVD_ADASUM_ACC_DTYPE", "f32")
    if name in ("f32", "float32"):
        return jnp.float32
    if name in ("f64", "float64"):
        if jax.config.jax_enable_x64:
            return jnp.float64
        if not _warned_no_x64:
            _warned_no_x64 = True
            get_logger().warning(
                "HVD_ADASUM_ACC_DTYPE=f64 requested but jax x64 is "
                "disabled (jax_enable_x64); keeping f32 islands")
        return jnp.float32
    raise ValueError(
        f"HVD_ADASUM_ACC_DTYPE={name!r}: expected 'f32' or 'f64'")


def _coefficients(a32: jax.Array, b32: jax.Array,
                  per_slice_axis0: bool = False):
    """acoeff/bcoeff per adasum.h:396-409, guarded for zero norms.

    ``per_slice_axis0``: compute INDEPENDENT coefficients per leading-axis
    slice (dots/norms reduce over every other axis).  This is how a
    ``scan_layers`` model's stacked [L, ...] parameter leaves keep the
    reference's per-tensor adaptation granularity — one coefficient pair
    per layer, not one joint pair across the whole stack."""
    axes = tuple(range(1, a32.ndim)) if per_slice_axis0 else None
    dot = jnp.sum(a32 * b32, axis=axes)
    na = jnp.sum(a32 * a32, axis=axes)
    nb = jnp.sum(b32 * b32, axis=axes)
    acoeff = jnp.where(na > 0, 1.0 - dot / jnp.where(na > 0, 2.0 * na, 1.0),
                       1.0)
    bcoeff = jnp.where(nb > 0, 1.0 - dot / jnp.where(nb > 0, 2.0 * nb, 1.0),
                       1.0)
    if per_slice_axis0:
        shape = (a32.shape[0],) + (1,) * (a32.ndim - 1)
        acoeff = acoeff.reshape(shape)
        bcoeff = bcoeff.reshape(shape)
    return acoeff, bcoeff


def pair_combine(a: jax.Array, b: jax.Array,
                 per_slice_axis0: bool = False) -> jax.Array:
    """Adasum of one pair; accumulation island dtype per ``_acc_dtype``
    (f32 default, HVD_ADASUM_ACC_DTYPE=f64 for reference-parity double)."""
    acc = _acc_dtype()
    a32 = a.astype(acc)
    b32 = b.astype(acc)
    acoeff, bcoeff = _coefficients(a32, b32, per_slice_axis0)
    return (acoeff * a32 + bcoeff * b32).astype(a.dtype)


def _tree_reduce_gathered(stacked: jax.Array,
                          per_slice_axis0: bool = False) -> jax.Array:
    """Binary-tree Adasum over a [n, ...] stack (non-pow2 fallback)."""
    import functools
    n = stacked.shape[0]
    pow2 = 1
    while pow2 < n:
        pow2 *= 2
    if pow2 != n:
        pad = jnp.zeros((pow2 - n,) + stacked.shape[1:], dtype=stacked.dtype)
        stacked = jnp.concatenate([stacked, pad], axis=0)
    combine = functools.partial(pair_combine,
                                per_slice_axis0=per_slice_axis0)
    while stacked.shape[0] > 1:
        stacked = jax.vmap(combine)(stacked[0::2], stacked[1::2])
    return stacked[0]


def adasum_allreduce(x: jax.Array,
                     *,
                     axis_name: str = "hvd",
                     members=None,
                     per_slice_axis0: bool = False) -> jax.Array:
    """Adasum allreduce over a mesh axis (ReduceOp.ADASUM dispatch target,
    message.h:46; AdasumMPIAllreduceOp analog).

    ``members``: optional static subset of slot indices (process set);
    non-member slots keep their input.  ``per_slice_axis0``: independent
    coefficients per leading-axis slice (see :func:`_coefficients`)."""
    n = lax.axis_size(axis_name) if members is None else len(members)
    if n == 1:
        return x
    is_pow2 = (n & (n - 1)) == 0
    if members is None and is_pow2:
        full = lax.axis_size(axis_name)
        levels = n.bit_length() - 1
        for level in range(levels):
            bit = 1 << level
            perm = [(i, i ^ bit) for i in range(full)]
            partner = lax.ppermute(x, axis_name, perm)
            # Keep the pair orientation identical on both partners so they
            # compute bit-identical results: "a" is always the lower index.
            idx = lax.axis_index(axis_name)
            is_lower = (idx & bit) == 0
            a = jnp.where(is_lower, x, partner)
            b = jnp.where(is_lower, partner, x)
            x = pair_combine(a, b, per_slice_axis0)
        return x
    stacked = lax.all_gather(x, axis_name, axis=0)
    if members is not None:
        sel = stacked[jnp.asarray(members, dtype=jnp.int32)]
        r = _tree_reduce_gathered(sel, per_slice_axis0)
        idx = lax.axis_index(axis_name)
        mask = jnp.isin(idx, jnp.asarray(members, dtype=jnp.int32))
        return jnp.where(mask, r, x)
    return _tree_reduce_gathered(stacked, per_slice_axis0)
