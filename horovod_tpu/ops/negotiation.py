"""Multi-controller eager negotiation — the reference's coordinator/worker
protocol, C++ logic + HTTP-KV transport.

Why this exists (SURVEY.md §7 "hard parts"): on the eager path, each process
issues collectives in whatever order its Python code reaches them.  If two
ranks disagree on order (or on a tensor's shape/dtype), the compiled XLA
collectives deadlock on ICI with no diagnosis.  The reference solves this
with rank-0 negotiation (controller.cc:74): every rank announces readiness,
rank 0 validates consistency (ConstructResponse, controller.cc:496) and
broadcasts the verdict; a ResponseCache (response_cache.h:45) skips the
round-trip for tensors already negotiated; a StallInspector
(stall_inspector.h:30) reports which ranks are missing when a collective
stalls >60 s.

The *logic* (message table, response cache, stall inspector) is the native
core (csrc/hvd_core.cc); this module supplies the transport: requests and
verdicts travel through the launcher's rendezvous KV store (the Gloo HTTP
store pattern) instead of MPI_Gatherv/Bcast.  The compiled (jit) path never
enters here — under jit, issue order is program order and XLA enforces it
(the reference itself disables cycling for its XLA path,
operations.cc:528-534).

Cost model (round 4): a *new* tensor signature costs ONE KV round-trip on
non-coordinator ranks (put_wait: announce the request and await the verdict
server-side) and zero on the coordinator (its signature feeds the message
table locally; the verdict is the return value).  A *cached* dispatch costs
zero synchronous round-trips: its replay-stream record is buffered locally
and shipped by the flusher thread in one batch-put per cycle — the same
amortization the reference gets from folding all cache coherence into one
bitvector collective per ~1 ms controller cycle (controller.cc:845
CoordinateCacheAndState).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .. import config as _config
from ..exceptions import HorovodInternalError
from ..utils import get_logger

# Op-kind id bases; the per-op parameter (ReduceOp value, broadcast root)
# is folded in so joined ranks can reconstruct the exact call from the
# signature alone.  Ranges are disjoint; allgather-family ids are >= 1000
# (the native Validate() relaxes dim0 matching for those).
KIND_IDS = {
    "allreduce": 0,             # + ReduceOp (0..5)
    "alltoall": 300,
    "reducescatter": 400,       # + ReduceOp
    "barrier": 500,
    "grouped_allreduce": 600,   # + ReduceOp
    "allgather": 1000,          # allgather-family: ids in [1000, 2000)
    "allgather_sizes": 1001,
    "broadcast": 10000,         # + root rank (unbounded above; own range)
}


def _kv_guarded(fn):
    """Decorator mapping dead-transport KV errors to HorovodInternalError
    (see Negotiator._map_transport_error)."""
    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        try:
            return fn(self, *a, **kw)
        except Exception as e:
            Negotiator._map_transport_error(e)
            raise
    return wrapper


class Negotiator:
    """Per-process negotiation endpoint.  Rank 0 doubles as coordinator."""

    def __init__(self, rank: int, size: int, cfg):
        self.rank = rank
        self.size = size
        self.cfg = cfg
        addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
        port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
        self.enabled = (size > 1 and addr is not None and port is not None)
        if not self.enabled:
            return
        from ..csrc import (NativeMessageTable, NativeResponseCache,
                            NativeStallInspector, CACHE_HIT, CACHE_INVALID)
        from ..runner.http_server import KVStoreClient
        self._HIT, self._INVALID = CACHE_HIT, CACHE_INVALID
        self.client = KVStoreClient(addr, int(port))
        self.cache = NativeResponseCache(cfg.cache_capacity)
        self.msgtable = NativeMessageTable(size) if rank == 0 else None
        self.stall = NativeStallInspector(
            cfg.stall_warning_time_seconds if cfg.stall_check_enabled
            else float("inf"),
            cfg.stall_shutdown_time_seconds, size)
        self._epochs: Dict[str, int] = {}
        self._inval_seen = 0  # last observed cross-rank invalidation seq
        self._inval_marker = None  # last-seen shared change marker bytes
        # Negotiation generation: bumped by elastic resets (all ranks reset
        # together) so a fresh negotiator never consumes KV records left by
        # its previous incarnation — stale verdicts would let one rank race
        # past a renegotiation and deadlock the rest.
        self._gen = os.environ.get("HVD_TPU_NEGOTIATION_GEN", "0")
        self.join_round = 0
        # Replayable dispatch stream (the join protocol's backbone): every
        # multiproc dispatch — cached or negotiated — appends a (seq,
        # signature) record to this rank's ring-buffered KV stream.  Ranks
        # advance in lockstep (same collectives, same program order), so
        # seq N names the same collective on every rank.
        self.dispatch_seq = 0
        self._ring = int(os.environ.get("HVD_TPU_DISPATCH_RING", "1024"))
        self._timeout = float(os.environ.get(
            _config.HOROVOD_GLOO_TIMEOUT_SECONDS, "300"))
        # Per-cycle batched stream flush (the analog of the reference's
        # once-per-cycle bitvector exchange, controller.cc:845): cached
        # dispatches append records to a local buffer; a flusher thread
        # ships the whole buffer in ONE batch-put per cycle.  A dispatch
        # therefore costs no synchronous KV round-trip — record visibility
        # for joined peers lags at most one cycle, and the device
        # collective's asynchronous dispatch means the issuing rank never
        # blocks inside that window (JAX queues the execution; the Python
        # thread keeps running and the flusher keeps flushing).
        self._flush_interval = float(os.environ.get(
            "HVD_TPU_DISPATCH_FLUSH_MS", "3")) / 1e3
        self._buf: list = []
        self._buf_lock = threading.Lock()
        self._flush_lock = threading.Lock()  # serializes batch shipping
        self._flusher = None
        self._flush_error: Optional[BaseException] = None
        self._flush_error_logged = False
        # Pending-records signal: the flusher sleeps on this instead of a
        # fixed-interval poll — an idle rank costs ~1 wakeup/s, not 333/s
        # (np idle flushers at a 3 ms cadence were real scheduling pressure
        # on a one-core launcher host).
        self._buf_event = threading.Event()
        self._closed = False

    # -- protocol -------------------------------------------------------------

    def _req_scope(self, name: str, epoch: int) -> str:
        from urllib.parse import quote
        return f"rq@{self._gen}@{epoch}@{quote(name, safe='')}"

    @staticmethod
    def _map_transport_error(e: BaseException) -> None:
        """Map a dead KV transport to HorovodInternalError so the elastic
        retry loop owns it (restore last commit → reset → the reset path's
        rendezvous liveness check converts a dead LAUNCHER into a named
        RendezvousUnreachableError fail-fast instead of a raw
        ConnectionRefusedError killing the worker mid-dispatch).  HTTP
        status errors (server answered) pass through: the server is alive,
        the request was wrong — a programming error."""
        import http.client as _http
        dead = isinstance(e, (ConnectionError, TimeoutError,
                              _http.HTTPException)) or \
            (isinstance(e, OSError) and e.errno is not None)
        if dead:
            raise HorovodInternalError(
                f"rendezvous KV unreachable during negotiation: {e}") from e

    @_kv_guarded
    def negotiate(self, name: str, kind: str, dtype: str,
                  shape: Tuple[int, ...], op: int = 0,
                  prescale: float = 1.0, postscale: float = 1.0,
                  ps_id: int = 0, ps_ranks=None, timeline=None) -> None:
        """Block until every rank has announced this collective and rank 0
        validated consistency; raises HorovodInternalError on mismatch.

        Fast path: response-cache HIT dispatches immediately with no
        traffic."""
        if not self.enabled:
            return
        kind_id = KIND_IDS.get(kind, 0) + op
        self._absorb_remote_invalidations()
        status = self.cache.lookup(name, dtype, shape, kind_id, prescale,
                                   postscale, ps_id)
        sig = {"dtype": dtype, "shape": list(shape), "op": kind_id,
               "prescale": prescale, "postscale": postscale, "ps_id": ps_id}
        if ps_ranks is not None:
            # Membership list rides the wire alongside the hashed ps_id (see
            # ops._wire_ps): the coordinator exact-checks it (hash-collision
            # guard) and a joined rank resolves the set from it on replay.
            sig["ps_ranks"] = list(ps_ranks)
        if status == self._HIT:
            # Cache fast path: no negotiation round-trip, but the dispatch
            # is still PUBLISHED to this rank's replay stream — a rank that
            # joined a microsecond ago replays it from there with zeros.
            # This closes the join-onset race the old design had (a fresh
            # join_active read per cached dispatch still left one RTT where
            # a joined rank never learned of the collective; the analog of
            # the reference's per-cycle cache-bitvector sync,
            # controller.cc:845 CoordinateCacheAndState, is this stream).
            self.publish_dispatch(name, self._epochs.get(name, 0), sig, kind)
            return
        if status == self._INVALID:
            # Shape/param change: renegotiate under a fresh epoch AND tell
            # every other rank, whose cached HIT would otherwise dispatch
            # straight into a mismatched collective (the reference keeps
            # cache coherence with a per-cycle bitvector AND,
            # controller.cc:845 CoordinateCacheAndState; here an
            # invalidation marker in the KV store plays that role).
            self.cache.invalidate(name)
            self._publish_invalidation(name)
        epoch = self._epochs.get(name, 0)
        self._epochs[name] = epoch + 1
        scope = f"negotiate@{self._gen}"
        # Requests live in their OWN scope per (name, epoch): the
        # coordinator scans it in one O(size) request with plain rank keys
        # — scanning the shared negotiate scope would ship every cached
        # verdict ever published on each poll AND make rank parsing
        # ambiguous for user names that embed '/'.  quote() keeps the
        # scope a single URL path segment whatever the tensor name is.
        req_scope = self._req_scope(name, epoch)
        resp_key = f"resp/{name}/{epoch}"
        self.publish_dispatch(name, epoch, sig, kind)
        if timeline is not None:
            timeline.negotiate_start(name, kind.upper())
        try:
            if self.rank == 0:
                if epoch > 0:
                    # GC the previous epoch's verdict: everyone who needed it
                    # has moved on to this epoch (KV stays O(names x size)).
                    try:
                        self.client.delete(scope, f"resp/{name}/{epoch - 1}")
                    except Exception as e:
                        # Best-effort GC — but never silent (HVD009): a
                        # string of these means the KV store is growing.
                        get_logger().debug(
                            "verdict GC delete failed: %s", e)
                # The coordinator feeds its own signature to the message
                # table locally and learns the verdict as the return value
                # — no request PUT, no verdict GET.
                verdict = self._coordinate(name, epoch, sig, timeline, kind)
            else:
                # ONE round-trip: announce the request and await the
                # verdict server-side (put_wait).  At np=16 the request
                # count IS the latency floor of a negotiation, so folding
                # announce+await halves the worker cost.
                verdict = self._submit_and_wait(req_scope, sig, name,
                                                scope, resp_key)
        finally:
            if timeline is not None:
                timeline.negotiate_end(name, kind.upper())
        if verdict:
            from ..exceptions import CollectiveRejectedError
            raise CollectiveRejectedError(
                f"collective {name!r} rejected by coordinator: {verdict}")
        self.cache.put(name, dtype, shape, kind_id, prescale, postscale,
                       ps_id)

    # -- cross-rank cache invalidation ---------------------------------------

    def _publish_invalidation(self, name: str) -> None:
        seq = self._inval_seen + 1
        self._inval_seen = seq
        self.client.put(f"negotiate@{self._gen}", f"inval/{self.rank}",
                        json.dumps({"seq": seq, "name": name}).encode())
        # Update the shared change marker that gates peers' scans.  The
        # value is globally unique (per-rank seq is monotonic), so however
        # concurrent writes interleave, the final value always differs
        # from any value a peer cached before the newest invalidation —
        # a plain counter would be ABA-racy here.
        self.client.put(f"negotiate@{self._gen}", "inval_ver",
                        f"{self.rank}:{seq}".encode())

    def _absorb_remote_invalidations(self) -> None:
        """Before trusting a cache HIT, absorb other ranks' invalidation
        markers.  The peer scan is O(size) KV GETs, so it runs at most every
        50 ms (the reference amortizes the same coherence into one bitvector
        collective per 1 ms cycle).  Shape changes are rare; in the worst
        case a stale HIT inside the 50 ms window dispatches into a collective
        the renegotiating rank never joins, and that rank's negotiation
        times out with a named error — degraded diagnosis, never silent
        corruption."""
        now = time.time()
        if now - getattr(self, "_inval_check_ts", 0.0) < 0.05:
            return
        self._inval_check_ts = now
        # Steady state is ONE cheap GET per 50 ms: the version marker only
        # changes when some rank actually invalidated (shape changes are
        # rare).  Only then pay a scope scan — a per-rank GET loop here was
        # O(size) requests per 50 ms per rank, a third of the single
        # server's capacity at np=16.
        ver = self.client.get(f"negotiate@{self._gen}", "inval_ver")
        if ver == self._inval_marker:
            return
        self._inval_marker = ver
        scope = self.client.scan(f"negotiate@{self._gen}")
        for key, raw in scope.items():
            if not key.startswith("inval/"):
                continue
            r = int(key[len("inval/"):])
            if r == self.rank:
                continue
            rec = json.loads(raw)
            if rec["seq"] > getattr(self, f"_inval_seen_{r}", 0):
                setattr(self, f"_inval_seen_{r}", rec["seq"])
                self.cache.invalidate(rec["name"])

    # -- join protocol (JoinOp, collective_operations.h:308) -----------------
    #
    # A rank with no more data calls join(): it publishes a round-scoped
    # join marker carrying its dispatch_seq, then REPLAYS live ranks'
    # dispatch streams from that position (ops/eager.py EagerEngine.join),
    # zero-filling each record — the reference's joined-ranks-contribute-
    # zeros semantics — so SPMD execution stays total over all processes.
    # The cache fast path needs no suspension and no join_active read:
    # every dispatch is in the stream before it can block.  Replays
    # themselves negotiate/publish like any dispatch, which keeps every
    # rank's seq counter aligned across join rounds.  join() returns the id
    # of the last rank to join, on every rank.

    @_kv_guarded
    def publish_dispatch(self, name: str, epoch: int, sig: dict,
                         kind: str) -> None:
        """Append one replayable record to this rank's dispatch stream
        (ring-buffered in the KV store; slot reuse is the GC).

        The append is LOCAL: records accumulate in a buffer that the
        flusher thread ships once per cycle in a single batch-put — a
        cached dispatch costs zero synchronous KV round-trips, matching
        the reference's amortization of all cache-coherence traffic into
        one bitvector exchange per cycle (controller.cc:845).  A buffer
        occupancy of ring/4 forces an inline flush so slot reuse can never
        outrun visibility."""
        if self._flush_error is not None:
            err, self._flush_error = self._flush_error, None
            raise err
        self.dispatch_seq += 1
        rec = {"seq": self.dispatch_seq, "name": name, "epoch": epoch,
               "sig": sig, "kind": kind}
        with self._buf_lock:
            self._buf.append((f"{self.rank}/{self.dispatch_seq % self._ring}",
                              json.dumps(rec).encode()))
            pending = len(self._buf)
        if pending >= max(1, self._ring // 4):
            self.flush_dispatches()
        else:
            self._buf_event.set()
            if self._flusher is None:
                self._start_flusher()

    def flush_dispatches(self) -> None:
        """Ship every buffered stream record in one batch-put.  The flush
        lock serializes inline and flusher-thread flushes so batches land
        in seq order (an out-of-order ship could regress a reused ring
        slot to an older lap)."""
        with self._flush_lock:
            with self._buf_lock:
                if not self._buf:
                    return
                batch, self._buf = self._buf, []
            try:
                # Shipping INSIDE _flush_lock is the lock's whole job: it
                # serializes batch puts so re-queued records can never
                # interleave with a younger batch (stream-order holes).
                # Only the flusher and close() ever contend, both
                # ship-or-park paths — blocking here is the design.
                self.client.put_batch(  # hvdlint: disable=HVD201
                    f"disp@{self._gen}", dict(batch))
            except Exception:
                # Re-queue: a transient KV failure must not punch a
                # permanent hole in the replay stream (a joined peer
                # polling the dropped seq would hang to the join timeout).
                with self._buf_lock:
                    self._buf[:0] = batch
                raise

    def _start_flusher(self) -> None:
        with self._buf_lock:
            if self._flusher is not None or self._closed:
                return
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name=f"hvd-dispatch-flush-{self.rank}")
            self._flusher.start()

    def _flush_loop(self) -> None:
        while not self._closed:
            if not self._buf_event.wait(timeout=1.0):
                continue  # nothing pending: stay parked
            # Batch window: let the cycle's records accumulate, then ship
            # them all in one batch-put.
            time.sleep(self._flush_interval)
            self._buf_event.clear()
            try:
                self.flush_dispatches()
                self._flush_error_logged = False
            except Exception as e:
                # Surface on the dispatching thread: the next
                # publish_dispatch rethrows (a dead KV during an elastic
                # teardown window is routine; a healthy run maps it to
                # HorovodInternalError there).  ALSO log the first failure
                # of a streak: a rank done dispatching never publishes
                # again, and close() swallows — without this line a
                # persistent KV failure would be invisible while a joined
                # peer replaying this rank's stream times out.
                self._flush_error = e
                # Re-arm: the failed batch was re-queued into _buf, and a
                # rank done dispatching would otherwise never retry it
                # (the event was cleared above) — park-until-publish must
                # not strand re-queued records.
                self._buf_event.set()
                if not self._flush_error_logged:
                    self._flush_error_logged = True
                    get_logger().warning(
                        "dispatch-stream flush failed (records re-queued; "
                        "rethrown on next publish): %r", e)

    def close(self) -> None:
        """Stop the flusher and ship any pending records, BOUNDED: close
        runs inside shutdown()/atexit, and an unreachable rendezvous would
        otherwise block exit ~60 s in connect timeouts (slow worker death
        is exactly what the elastic teardown paths fight).  The flush runs
        in a daemon thread with a short join; abandoning records at
        process exit is fine — nobody will replay a dead generation."""
        self._closed = True
        # Wake a parked flusher so it observes _closed and exits now
        # instead of on its next 1 s poll; then join it bounded — close()
        # must leave no flusher behind on the happy path (daemon stays
        # the backstop when it is wedged in a dead-KV connect).
        self._buf_event.set()
        t = threading.Thread(target=lambda: self._swallow(
            self.flush_dispatches), daemon=True,
            name=f"hvd-dispatch-close-{self.rank}")
        t.start()
        t.join(2.0)
        flusher = self._flusher
        if flusher is not None and \
                flusher is not threading.current_thread():
            flusher.join(2.0)

    @staticmethod
    def _swallow(fn) -> None:
        try:
            fn()
        except Exception:
            pass

    @_kv_guarded
    def poll_dispatch(self, src: int, seq: int) -> Optional[dict]:
        """Record number ``seq`` from ``src``'s stream, or None if not yet
        published.  A newer record in the slot means the publisher lapped
        the ring before this rank replayed — unrecoverable, so fail loudly
        (elastic reset can recover the job)."""
        raw = self.client.get(f"disp@{self._gen}",
                              f"{src}/{seq % self._ring}")
        if raw is None:
            return None
        rec = json.loads(raw)
        if rec["seq"] == seq:
            return rec
        if rec["seq"] > seq:
            raise HorovodInternalError(
                f"join replay stream overrun: rank {src} is "
                f"{rec['seq'] - seq} dispatches ahead of this joined rank "
                f"(ring size {self._ring}; raise HVD_TPU_DISPATCH_RING)")
        return None  # slot still holds an older lap's record

    @_kv_guarded
    def join_active(self) -> bool:
        """True while some rank's join round is open (used by the
        coordinator's broadcast-root check; NOT on the dispatch hot path —
        the replay stream made that read unnecessary)."""
        return self.client.get(f"join@{self._gen}", "active") is not None

    def joined_ranks(self, round_: int) -> dict:
        """rank -> {"order": timestamp, "seq": final dispatch seq} for the
        given join round."""
        out = {}
        for r in range(self.size):
            m = self.join_marker(round_, r)
            if m is not None:
                out[r] = m
        return out

    @_kv_guarded
    def join_marker(self, round_: int, rank: int) -> Optional[dict]:
        """One rank's join marker for the round (fresh read), or None."""
        raw = self.client.get(f"join{round_}@{self._gen}", str(rank))
        return None if raw is None else json.loads(raw)

    @_kv_guarded
    def announce_join(self, round_: int) -> None:
        self.client.put(f"join@{self._gen}", "active", b"1")
        self.client.put(f"join{round_}@{self._gen}", str(self.rank),
                        json.dumps({"order": time.time(),
                                    "seq": self.dispatch_seq}).encode())

    def finish_join_round(self, round_: int, last_rank: int) -> None:
        """The last-joining rank retires the round."""
        if self.rank == last_rank:
            try:
                self.client.delete(f"join@{self._gen}", "active")
            except Exception as e:
                get_logger().debug(
                    "join-round retire delete failed: %s", e)

    def _submit_and_wait(self, req_scope: str, sig: dict, name: str,
                         scope: str, resp_key: str) -> str:
        """Non-coordinator rank: one put_wait round-trip announces the
        request and returns the verdict.  On a wait-chunk timeout the
        request is re-put (idempotent; the coordinator's arrived-set
        dedups)."""
        body = json.dumps(sig).encode()
        deadline = time.time() + self._timeout
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                raise HorovodInternalError(
                    f"timed out waiting for negotiation verdict on {name!r}")
            raw = self.client.put_wait(req_scope, str(self.rank), body,
                                       scope, resp_key,
                                       wait=min(remaining, 5.0))
            if raw is not None:
                return json.loads(raw).get("error", "")

    def _coordinate(self, name: str, epoch: int, my_sig: dict,
                    timeline, kind: str = "allreduce") -> str:
        """Rank 0: gather all ranks' requests, run the native message table,
        publish the verdict (ComputeResponseList slow path) and return it
        ("" = approved).

        The message table is keyed per (name, epoch) and unconditionally
        erased on every exit path — an error verdict (timeout, duplicate,
        stall shutdown) must not poison the name for the elastic retry.

        Join-awareness: joined ranks replay the dispatch stream, so their
        requests arrive here like any other rank's — no special casing
        except the broadcast-root check (a joined root has no data to
        broadcast; zeros would be silently wrong, so it is an error, the
        reference's JoinOp + broadcast semantics)."""
        tbl_key = f"{name}#{epoch}"
        deadline = time.time() + self._timeout
        arrived = set()
        last_stall_check = time.time()
        req_scope = self._req_scope(name, epoch)
        first_ps_ranks = my_sig.get("ps_ranks")
        try:
            # The coordinator's own signature enters the table directly —
            # its request never touches the KV store.
            res = self.msgtable.increment(
                tbl_key, my_sig["dtype"], my_sig["shape"], my_sig["op"], 0,
                my_sig["prescale"], my_sig["postscale"], my_sig["ps_id"])
            if res == -1:
                return self._publish(name, epoch,
                                     "duplicate request from rank 0 "
                                     "(DUPLICATE_NAME_ERROR)")
            arrived.add(0)
            self.stall.record_request(tbl_key, 0, time.time())
            if timeline is not None:
                timeline.negotiate_rank_ready(name, 0)
            while len(arrived) < self.size:
                # ONE dedicated-scope scan per poll collects every rank's
                # request (keys are plain rank numbers; rank 0's never
                # hits the KV, hence size-1) — a per-rank GET loop is
                # O(size) requests per 10 ms and starves the server at
                # np >= 16.  The scan long-polls until all requests are
                # present (or 1 s passes for a stall check), so the
                # last-arriving rank wakes the coordinator immediately
                # instead of landing in a 10 ms sleep quantum.
                scope = self.client.scan(req_scope, wait=1.0,
                                         min_keys=self.size - 1)
                for key, raw in scope.items():
                    r = int(key)
                    if r in arrived:
                        continue
                    sig = json.loads(raw)
                    res = self.msgtable.increment(
                        tbl_key, sig["dtype"], sig["shape"], sig["op"], r,
                        sig["prescale"], sig["postscale"], sig["ps_id"])
                    if res == -1:
                        return self._publish(
                            name, epoch,
                            f"duplicate request from rank {r} "
                            f"(DUPLICATE_NAME_ERROR)")
                    # Exact membership check: ps_id is a membership hash
                    # (ops._wire_ps), so the native table already rejects
                    # different memberships; this closes the residual
                    # hash-collision window with the rank lists themselves.
                    if sig.get("ps_ranks") != first_ps_ranks:
                        return self._publish(
                            name, epoch,
                            f"process-set membership mismatch on {name!r}: "
                            f"rank {r} announced {sig.get('ps_ranks')} vs "
                            f"{first_ps_ranks}")
                    arrived.add(r)
                    self.stall.record_request(tbl_key, r, time.time())
                    if timeline is not None:
                        timeline.negotiate_rank_ready(name, r)
                now = time.time()
                if now - last_stall_check > 1.0:
                    last_stall_check = now
                    st, report = self.stall.check(now)
                    if st >= 1:
                        for tname, waited, ready, missing in report:
                            get_logger().warning(
                                "Stalled collective %s: waited %.0fs; ready "
                                "ranks %s; missing ranks %s "
                                "(HOROVOD_STALL_CHECK_TIME_SECONDS)",
                                tname.split("#")[0], waited, ready, missing)
                    if st == 2:
                        return self._publish(
                            name, epoch,
                            "stall shutdown threshold exceeded")
                if now > deadline:
                    return self._publish(
                        name, epoch,
                        f"negotiation timed out; arrived={sorted(arrived)}")
                # No sleep: the scan above long-polls server-side until
                # every rank's request is present.
            if kind == "broadcast" and self.join_active():
                root = my_sig["op"] - KIND_IDS["broadcast"]
                if root in self.joined_ranks(
                        getattr(self, "join_round", 0)):
                    return self._publish(
                        name, epoch,
                        f"broadcast root rank {root} has joined "
                        f"(no data to broadcast)")
            # Native validation errors embed the epoch-scoped table key;
            # surface the user-facing name instead.
            return self._publish(
                name, epoch,
                self.msgtable.validate(tbl_key).replace(tbl_key, name))
        finally:
            self.stall.record_done(tbl_key)
            self.msgtable.erase(tbl_key)
            # GC the request scope in ONE request, after the verdict is
            # published (workers only re-put while the verdict is absent;
            # a re-put racing this delete leaks at most one key of an
            # epoch-scoped scope, never consumed again).
            try:
                self.client.delete_scope(req_scope)
            except Exception as e:
                get_logger().debug(
                    "request-scope GC failed for %s: %s", req_scope, e)

    def _publish(self, name: str, epoch: int, err: str) -> str:
        """Publish the verdict for the waiting ranks; return it for the
        coordinator's own caller."""
        self.client.put(f"negotiate@{self._gen}", f"resp/{name}/{epoch}",
                        json.dumps({"error": err}).encode())
        return err
