"""Multi-controller eager negotiation — the reference's coordinator/worker
protocol, C++ logic + HTTP-KV transport.

Why this exists (SURVEY.md §7 "hard parts"): on the eager path, each process
issues collectives in whatever order its Python code reaches them.  If two
ranks disagree on order (or on a tensor's shape/dtype), the compiled XLA
collectives deadlock on ICI with no diagnosis.  The reference solves this
with rank-0 negotiation (controller.cc:74): every rank announces readiness,
rank 0 validates consistency (ConstructResponse, controller.cc:496) and
broadcasts the verdict; a ResponseCache (response_cache.h:45) skips the
round-trip for tensors already negotiated; a StallInspector
(stall_inspector.h:30) reports which ranks are missing when a collective
stalls >60 s.

The *logic* (message table, response cache, stall inspector) is the native
core (csrc/hvd_core.cc); this module supplies the transport: requests and
verdicts travel through the launcher's rendezvous KV store (the Gloo HTTP
store pattern) instead of MPI_Gatherv/Bcast.  The compiled (jit) path never
enters here — under jit, issue order is program order and XLA enforces it
(the reference itself disables cycling for its XLA path,
operations.cc:528-534).

Cost model: two KV round-trips per *new* tensor signature; repeat
submissions hit the native response cache and dispatch immediately, which is
the same steady-state the reference reaches via its bitvector fast path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .. import config as _config
from ..exceptions import HorovodInternalError, DuplicateNameError
from ..utils import get_logger

# Op-kind ids for cross-rank match checking; allgather-family ids are >= 100
# (the native Validate() relaxes dim0 matching for those).
KIND_IDS = {
    "allreduce": 0,        # + ReduceOp enum value is folded into params
    "grouped_allreduce": 1,
    "broadcast": 10,
    "alltoall": 20,
    "reducescatter": 30,
    "barrier": 40,
    "allgather": 100,
    "allgather_sizes": 101,
}


class Negotiator:
    """Per-process negotiation endpoint.  Rank 0 doubles as coordinator."""

    def __init__(self, rank: int, size: int, cfg):
        self.rank = rank
        self.size = size
        self.cfg = cfg
        addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
        port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
        self.enabled = (size > 1 and addr is not None and port is not None)
        if not self.enabled:
            return
        from ..csrc import (NativeMessageTable, NativeResponseCache,
                            NativeStallInspector, CACHE_HIT, CACHE_INVALID)
        from ..runner.http_server import KVStoreClient
        self._HIT, self._INVALID = CACHE_HIT, CACHE_INVALID
        self.client = KVStoreClient(addr, int(port))
        self.cache = NativeResponseCache(cfg.cache_capacity)
        self.msgtable = NativeMessageTable(size) if rank == 0 else None
        self.stall = NativeStallInspector(
            cfg.stall_warning_time_seconds if cfg.stall_check_enabled
            else float("inf"),
            cfg.stall_shutdown_time_seconds, size)
        self._epochs: Dict[str, int] = {}
        self._inval_seen = 0  # last observed cross-rank invalidation seq
        self._timeout = float(os.environ.get(
            _config.HOROVOD_GLOO_TIMEOUT_SECONDS, "300"))

    # -- protocol -------------------------------------------------------------

    def negotiate(self, name: str, kind: str, dtype: str,
                  shape: Tuple[int, ...], op: int = 0,
                  prescale: float = 1.0, postscale: float = 1.0,
                  ps_id: int = 0, timeline=None) -> None:
        """Block until every rank has announced this collective and rank 0
        validated consistency; raises HorovodInternalError on mismatch.

        Fast path: response-cache HIT dispatches immediately with no
        traffic."""
        if not self.enabled:
            return
        kind_id = KIND_IDS.get(kind, 0) + (op if kind == "allreduce" else 0)
        self._absorb_remote_invalidations()
        status = self.cache.lookup(name, dtype, shape, kind_id, prescale,
                                   postscale, ps_id)
        if status == self._HIT:
            return
        if status == self._INVALID:
            # Shape/param change: renegotiate under a fresh epoch AND tell
            # every other rank, whose cached HIT would otherwise dispatch
            # straight into a mismatched collective (the reference keeps
            # cache coherence with a per-cycle bitvector AND,
            # controller.cc:845 CoordinateCacheAndState; here an
            # invalidation marker in the KV store plays that role).
            self.cache.invalidate(name)
            self._publish_invalidation(name)
        epoch = self._epochs.get(name, 0)
        self._epochs[name] = epoch + 1
        scope = "negotiate"
        req_key = f"req/{name}/{epoch}/{self.rank}"
        resp_key = f"resp/{name}/{epoch}"
        sig = {"dtype": dtype, "shape": list(shape), "op": kind_id,
               "prescale": prescale, "postscale": postscale, "ps_id": ps_id}
        if timeline is not None:
            timeline.negotiate_start(name, kind.upper())
        self.client.put(scope, req_key, json.dumps(sig).encode())
        try:
            if self.rank == 0:
                if epoch > 0:
                    # GC the previous epoch's verdict: everyone who needed it
                    # has moved on to this epoch (KV stays O(names x size)).
                    try:
                        self.client.delete(scope, f"resp/{name}/{epoch - 1}")
                    except Exception:
                        pass
                self._coordinate(name, epoch, sig, timeline)
            verdict = self._wait_response(name, resp_key)
            # Own request record is consumed; drop it.
            try:
                self.client.delete(scope, req_key)
            except Exception:
                pass
        finally:
            if timeline is not None:
                timeline.negotiate_end(name, kind.upper())
        if verdict:
            raise HorovodInternalError(
                f"collective {name!r} rejected by coordinator: {verdict}")
        self.cache.put(name, dtype, shape, kind_id, prescale, postscale,
                       ps_id)

    # -- cross-rank cache invalidation ---------------------------------------

    def _publish_invalidation(self, name: str) -> None:
        seq = self._inval_seen + 1
        self._inval_seen = seq
        self.client.put("negotiate", f"inval/{self.rank}",
                        json.dumps({"seq": seq, "name": name}).encode())

    def _absorb_remote_invalidations(self) -> None:
        """Before trusting a cache HIT, absorb other ranks' invalidation
        markers (one KV GET per peer per dispatch — the eager path trades a
        millisecond for coherence; the compiled path never pays this)."""
        for r in range(self.size):
            if r == self.rank:
                continue
            raw = self.client.get("negotiate", f"inval/{r}")
            if raw is None:
                continue
            rec = json.loads(raw)
            if rec["seq"] > getattr(self, f"_inval_seen_{r}", 0):
                setattr(self, f"_inval_seen_{r}", rec["seq"])
                self.cache.invalidate(rec["name"])

    def _coordinate(self, name: str, epoch: int, my_sig: dict,
                    timeline) -> None:
        """Rank 0: gather all ranks' requests, run the native message table,
        publish the verdict (ComputeResponseList slow path).

        The message table is keyed per (name, epoch) and unconditionally
        erased on every exit path — an error verdict (timeout, duplicate,
        stall shutdown) must not poison the name for the elastic retry."""
        tbl_key = f"{name}#{epoch}"
        deadline = time.time() + self._timeout
        arrived = set()
        last_stall_check = time.time()
        try:
            while len(arrived) < self.size:
                for r in range(self.size):
                    if r in arrived:
                        continue
                    raw = self.client.get("negotiate",
                                          f"req/{name}/{epoch}/{r}")
                    if raw is None:
                        continue
                    sig = json.loads(raw)
                    res = self.msgtable.increment(
                        tbl_key, sig["dtype"], sig["shape"], sig["op"], r,
                        sig["prescale"], sig["postscale"], sig["ps_id"])
                    if res == -1:
                        self._publish(name, epoch,
                                      f"duplicate request from rank {r} "
                                      f"(DUPLICATE_NAME_ERROR)")
                        return
                    arrived.add(r)
                    self.stall.record_request(tbl_key, r, time.time())
                    if timeline is not None:
                        timeline.negotiate_rank_ready(name, r)
                now = time.time()
                if now - last_stall_check > 1.0:
                    last_stall_check = now
                    st, report = self.stall.check(now)
                    if st >= 1:
                        for tname, waited, ready, missing in report:
                            get_logger().warning(
                                "Stalled collective %s: waited %.0fs; ready "
                                "ranks %s; missing ranks %s "
                                "(HOROVOD_STALL_CHECK_TIME_SECONDS)",
                                tname.split("#")[0], waited, ready, missing)
                    if st == 2:
                        self._publish(name, epoch,
                                      "stall shutdown threshold exceeded")
                        return
                if now > deadline:
                    self._publish(
                        name, epoch,
                        f"negotiation timed out; arrived={sorted(arrived)}")
                    return
                if len(arrived) < self.size:
                    time.sleep(0.01)
            # Native validation errors embed the epoch-scoped table key;
            # surface the user-facing name instead.
            self._publish(name, epoch,
                          self.msgtable.validate(tbl_key).replace(tbl_key,
                                                                  name))
        finally:
            self.stall.record_done(tbl_key)
            self.msgtable.erase(tbl_key)

    def _publish(self, name: str, epoch: int, err: str) -> None:
        self.client.put("negotiate", f"resp/{name}/{epoch}",
                        json.dumps({"error": err}).encode())

    def _wait_response(self, name: str, resp_key: str) -> str:
        deadline = time.time() + self._timeout
        while time.time() < deadline:
            raw = self.client.get("negotiate", resp_key)
            if raw is not None:
                return json.loads(raw).get("error", "")
            time.sleep(0.005)
        raise HorovodInternalError(
            f"timed out waiting for negotiation verdict on {name!r}")
