"""Multi-controller eager negotiation — the reference's coordinator/worker
protocol, C++ logic + HTTP-KV transport.

Why this exists (SURVEY.md §7 "hard parts"): on the eager path, each process
issues collectives in whatever order its Python code reaches them.  If two
ranks disagree on order (or on a tensor's shape/dtype), the compiled XLA
collectives deadlock on ICI with no diagnosis.  The reference solves this
with rank-0 negotiation (controller.cc:74): every rank announces readiness,
rank 0 validates consistency (ConstructResponse, controller.cc:496) and
broadcasts the verdict; a ResponseCache (response_cache.h:45) skips the
round-trip for tensors already negotiated; a StallInspector
(stall_inspector.h:30) reports which ranks are missing when a collective
stalls >60 s.

The *logic* (message table, response cache, stall inspector) is the native
core (csrc/hvd_core.cc); this module supplies the transport: requests and
verdicts travel through the launcher's rendezvous KV store (the Gloo HTTP
store pattern) instead of MPI_Gatherv/Bcast.  The compiled (jit) path never
enters here — under jit, issue order is program order and XLA enforces it
(the reference itself disables cycling for its XLA path,
operations.cc:528-534).

Cost model: two KV round-trips per *new* tensor signature; repeat
submissions hit the native response cache and dispatch immediately, which is
the same steady-state the reference reaches via its bitvector fast path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

from .. import config as _config
from ..exceptions import HorovodInternalError
from ..utils import get_logger

# Op-kind id bases; the per-op parameter (ReduceOp value, broadcast root)
# is folded in so joined ranks can reconstruct the exact call from the
# signature alone.  Ranges are disjoint; allgather-family ids are >= 1000
# (the native Validate() relaxes dim0 matching for those).
KIND_IDS = {
    "allreduce": 0,             # + ReduceOp (0..5)
    "alltoall": 300,
    "reducescatter": 400,       # + ReduceOp
    "barrier": 500,
    "grouped_allreduce": 600,   # + ReduceOp
    "allgather": 1000,          # allgather-family: ids in [1000, 2000)
    "allgather_sizes": 1001,
    "broadcast": 10000,         # + root rank (unbounded above; own range)
}


class Negotiator:
    """Per-process negotiation endpoint.  Rank 0 doubles as coordinator."""

    def __init__(self, rank: int, size: int, cfg):
        self.rank = rank
        self.size = size
        self.cfg = cfg
        addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
        port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
        self.enabled = (size > 1 and addr is not None and port is not None)
        if not self.enabled:
            return
        from ..csrc import (NativeMessageTable, NativeResponseCache,
                            NativeStallInspector, CACHE_HIT, CACHE_INVALID)
        from ..runner.http_server import KVStoreClient
        self._HIT, self._INVALID = CACHE_HIT, CACHE_INVALID
        self.client = KVStoreClient(addr, int(port))
        self.cache = NativeResponseCache(cfg.cache_capacity)
        self.msgtable = NativeMessageTable(size) if rank == 0 else None
        self.stall = NativeStallInspector(
            cfg.stall_warning_time_seconds if cfg.stall_check_enabled
            else float("inf"),
            cfg.stall_shutdown_time_seconds, size)
        self._epochs: Dict[str, int] = {}
        self._inval_seen = 0  # last observed cross-rank invalidation seq
        # Negotiation generation: bumped by elastic resets (all ranks reset
        # together) so a fresh negotiator never consumes KV records left by
        # its previous incarnation — stale verdicts would let one rank race
        # past a renegotiation and deadlock the rest.
        self._gen = os.environ.get("HVD_TPU_NEGOTIATION_GEN", "0")
        self.join_round = 0
        self._coordinating = set()     # (name, epoch) in a bg thread NOW
        self._coordinated_done = set()  # (name, epoch) already coordinated
        self._coord_lock = threading.Lock()
        self._timeout = float(os.environ.get(
            _config.HOROVOD_GLOO_TIMEOUT_SECONDS, "300"))

    # -- protocol -------------------------------------------------------------

    def negotiate(self, name: str, kind: str, dtype: str,
                  shape: Tuple[int, ...], op: int = 0,
                  prescale: float = 1.0, postscale: float = 1.0,
                  ps_id: int = 0, timeline=None) -> None:
        """Block until every rank has announced this collective and rank 0
        validated consistency; raises HorovodInternalError on mismatch.

        Fast path: response-cache HIT dispatches immediately with no
        traffic."""
        if not self.enabled:
            return
        kind_id = KIND_IDS.get(kind, 0) + op
        self._absorb_remote_invalidations()
        status = self.cache.lookup(name, dtype, shape, kind_id, prescale,
                                   postscale, ps_id)
        if status == self._HIT and not self.join_active():
            # Cache fast path — suspended while any rank is joined so the
            # coordinator can keep publishing joinop records (the bitvector-
            # sync analog, controller.cc:845).
            return
        if status == self._INVALID:
            # Shape/param change: renegotiate under a fresh epoch AND tell
            # every other rank, whose cached HIT would otherwise dispatch
            # straight into a mismatched collective (the reference keeps
            # cache coherence with a per-cycle bitvector AND,
            # controller.cc:845 CoordinateCacheAndState; here an
            # invalidation marker in the KV store plays that role).
            self.cache.invalidate(name)
            self._publish_invalidation(name)
        epoch = self._epochs.get(name, 0)
        self._epochs[name] = epoch + 1
        scope = f"negotiate@{self._gen}"
        req_key = f"req/{name}/{epoch}/{self.rank}"
        resp_key = f"resp/{name}/{epoch}"
        sig = {"dtype": dtype, "shape": list(shape), "op": kind_id,
               "prescale": prescale, "postscale": postscale, "ps_id": ps_id}
        if timeline is not None:
            timeline.negotiate_start(name, kind.upper())
        self.client.put(scope, req_key, json.dumps(sig).encode())
        self._maybe_announce(name, epoch, sig, kind)
        try:
            with self._coord_lock:
                bg_coordinated = ((name, epoch) in self._coordinating or
                                  (name, epoch) in self._coordinated_done)
            if self.rank == 0 and not bg_coordinated:
                if epoch > 0:
                    # GC the previous epoch's verdict: everyone who needed it
                    # has moved on to this epoch (KV stays O(names x size)).
                    try:
                        self.client.delete(scope, f"resp/{name}/{epoch - 1}")
                    except Exception:
                        pass
                self._coordinate(name, epoch, sig, timeline, kind)
            verdict = self._wait_response(name, resp_key,
                                          reannounce=(epoch, sig, kind))
            # Own request record is consumed; drop it.
            try:
                self.client.delete(scope, req_key)
            except Exception:
                pass
        finally:
            if timeline is not None:
                timeline.negotiate_end(name, kind.upper())
        if verdict:
            raise HorovodInternalError(
                f"collective {name!r} rejected by coordinator: {verdict}")
        self.cache.put(name, dtype, shape, kind_id, prescale, postscale,
                       ps_id)

    # -- cross-rank cache invalidation ---------------------------------------

    def _publish_invalidation(self, name: str) -> None:
        seq = self._inval_seen + 1
        self._inval_seen = seq
        self.client.put(f"negotiate@{self._gen}", f"inval/{self.rank}",
                        json.dumps({"seq": seq, "name": name}).encode())

    def _absorb_remote_invalidations(self) -> None:
        """Before trusting a cache HIT, absorb other ranks' invalidation
        markers.  The peer scan is O(size) KV GETs, so it runs at most every
        50 ms (the reference amortizes the same coherence into one bitvector
        collective per 1 ms cycle).  Shape changes are rare; in the worst
        case a stale HIT inside the 50 ms window dispatches into a collective
        the renegotiating rank never joins, and that rank's negotiation
        times out with a named error — degraded diagnosis, never silent
        corruption."""
        now = time.time()
        if now - getattr(self, "_inval_check_ts", 0.0) < 0.05:
            return
        self._inval_check_ts = now
        for r in range(self.size):
            if r == self.rank:
                continue
            raw = self.client.get(f"negotiate@{self._gen}", f"inval/{r}")
            if raw is None:
                continue
            rec = json.loads(raw)
            if rec["seq"] > getattr(self, f"_inval_seen_{r}", 0):
                setattr(self, f"_inval_seen_{r}", rec["seq"])
                self.cache.invalidate(rec["name"])

    # -- join protocol (JoinOp, collective_operations.h:308) -----------------
    #
    # A rank with no more data calls join(): it publishes a round-scoped
    # join marker and enters a service loop (ops/eager.py EagerEngine.join).
    # While any rank is joined, the cache fast path is suspended (every op
    # negotiates — the analog of the reference's per-cycle bitvector sync
    # keeping joined ranks in the loop).  When the coordinator sees that the
    # only missing ranks are joined ones, it publishes a "joinop" record
    # describing the pending collective; each joined rank's service loop
    # dispatches the SAME collective with zero tensors (the reference's
    # joined-ranks-contribute-zeros semantics), so SPMD execution stays
    # total over all processes.  join() returns the id of the last rank to
    # join, on every rank.

    def join_active(self) -> bool:
        """Fresh KV read every call: a cached (un-negotiated) dispatch issued
        after a peer joined would block in a collective the joined rank's
        service loop never learns about, so the fast path must see the join
        marker as soon as it exists.  (A sub-millisecond window remains
        between this read and the dispatch — closing it fully needs cached
        dispatches to publish replayable signatures; see TODO.md.)"""
        val = self.client.get(f"join@{self._gen}", "active") is not None
        self._join_check_val = val
        return val

    def joined_ranks(self, round_: int) -> dict:
        """rank -> join order timestamp for the given join round."""
        out = {}
        for r in range(self.size):
            raw = self.client.get(f"join{round_}@{self._gen}", str(r))
            if raw is not None:
                out[r] = json.loads(raw)["order"]
        return out

    def announce_join(self, round_: int) -> None:
        self.client.put(f"join@{self._gen}", "active", b"1")
        self.client.put(f"join{round_}@{self._gen}", str(self.rank),
                        json.dumps({"order": time.time()}).encode())
        self._join_check_val = True
        self._join_check_ts = time.time()

    def finish_join_round(self, round_: int, last_rank: int) -> None:
        """The last-joining rank retires the round."""
        if self.rank == last_rank:
            try:
                self.client.delete(f"join@{self._gen}", "active")
            except Exception:
                pass
        self._join_check_val = False
        self._join_check_ts = 0.0
        with self._coord_lock:
            self._coordinated_done.clear()
        if hasattr(self, "_announced"):
            self._announced.clear()

    def _maybe_announce(self, name: str, epoch: int, sig: dict,
                        kind: str) -> None:
        """If the coordinator (rank 0) has joined, the lowest-ranked survivor
        announces the op so rank 0's service loop coordinates it.  Called at
        submit time AND periodically while waiting for the verdict — rank 0
        may join a moment after the first check (duplicate announcements are
        deduped coordinator-side against the coordinated set)."""
        if self.rank == 0 or not self.join_active():
            return
        joined = set(self.joined_ranks(self.join_round).keys())
        if 0 not in joined:
            return
        survivors = [r for r in range(self.size) if r not in joined]
        if not survivors or self.rank != min(survivors):
            return
        key = (name, epoch)
        announced = getattr(self, "_announced", set())
        self._announced = announced
        if key in announced:
            return
        announced.add(key)
        self._announce_for_coordinator(name, epoch, sig, kind)

    def _announce_for_coordinator(self, name: str, epoch: int, sig: dict,
                                  kind: str) -> None:
        self._annc_seq = getattr(self, "_annc_seq", 0) + 1
        self.client.put(f"annc@{self._gen}", f"{self.rank}/{self._annc_seq}",
                        json.dumps({"name": name, "epoch": epoch,
                                    "sig": sig, "kind": kind}).encode())
        self.client.put(f"annc@{self._gen}", f"{self.rank}/seq",
                        str(self._annc_seq).encode())

    def service_announcements(self, seen: Dict[int, int]) -> None:
        """Joined rank 0: coordinate ops announced by survivors.  Each new
        announcement spawns a coordination thread (the op's verdict and
        joinop record flow exactly as in the inline path); the (name, epoch)
        is marked so rank 0's own zero-dispatch doesn't coordinate twice."""
        for r in range(1, self.size):
            raw = self.client.get(f"annc@{self._gen}", f"{r}/seq")
            if raw is None:
                continue
            latest = int(raw)
            while seen.get(r, 0) < latest:
                s = seen.get(r, 0) + 1
                seen[r] = s
                rec = json.loads(self.client.get(f"annc@{self._gen}", f"{r}/{s}"))
                key = (rec["name"], rec["epoch"])
                with self._coord_lock:
                    if key in self._coordinating or \
                            key in self._coordinated_done:
                        continue
                    self._coordinating.add(key)

                def coordinate(rec=rec, key=key):
                    try:
                        self._coordinate(rec["name"], rec["epoch"],
                                         rec["sig"], None, rec["kind"])
                    finally:
                        with self._coord_lock:
                            # Record completion BEFORE leaving the
                            # in-flight set: rank 0's own zero-dispatch must
                            # never re-coordinate a finished epoch.
                            self._coordinated_done.add(key)
                            self._coordinating.discard(key)

                threading.Thread(target=coordinate, daemon=True,
                                 name="hvd-join-coord").start()

    def publish_joinop(self, name: str, epoch: int, sig: dict,
                       kind: str) -> None:
        self._joinop_seq = getattr(self, "_joinop_seq", 0) + 1
        self.client.put(f"joinops@{self._gen}", str(self._joinop_seq),
                        json.dumps({"name": name, "epoch": epoch,
                                    "sig": sig, "kind": kind}).encode())
        self.client.put(f"joinops@{self._gen}", "seq",
                        str(self._joinop_seq).encode())

    def poll_joinop(self, seen: int):
        raw = self.client.get(f"joinops@{self._gen}", "seq")
        if raw is None:
            return seen, None
        seq = int(raw)
        if seq <= seen:
            return seen, None
        rec = json.loads(self.client.get(f"joinops@{self._gen}",
                                         str(seen + 1)))
        return seen + 1, rec

    def _coordinate(self, name: str, epoch: int, my_sig: dict,
                    timeline, kind: str = "allreduce") -> None:
        """Rank 0: gather all ranks' requests, run the native message table,
        publish the verdict (ComputeResponseList slow path).

        The message table is keyed per (name, epoch) and unconditionally
        erased on every exit path — an error verdict (timeout, duplicate,
        stall shutdown) must not poison the name for the elastic retry.

        Join-awareness: when every missing rank has a join marker, publish a
        joinop record so their service loops contribute zeros; their
        requests then arrive like any other rank's."""
        tbl_key = f"{name}#{epoch}"
        deadline = time.time() + self._timeout
        arrived = set()
        last_stall_check = time.time()
        joinop_published = False
        try:
            while len(arrived) < self.size:
                for r in range(self.size):
                    if r in arrived:
                        continue
                    raw = self.client.get(f"negotiate@{self._gen}",
                                          f"req/{name}/{epoch}/{r}")
                    if raw is None:
                        continue
                    sig = json.loads(raw)
                    res = self.msgtable.increment(
                        tbl_key, sig["dtype"], sig["shape"], sig["op"], r,
                        sig["prescale"], sig["postscale"], sig["ps_id"])
                    if res == -1:
                        self._publish(name, epoch,
                                      f"duplicate request from rank {r} "
                                      f"(DUPLICATE_NAME_ERROR)")
                        return
                    arrived.add(r)
                    self.stall.record_request(tbl_key, r, time.time())
                    if timeline is not None:
                        timeline.negotiate_rank_ready(name, r)
                now = time.time()
                if not joinop_published and len(arrived) < self.size and \
                        self.join_active():
                    missing = set(range(self.size)) - arrived
                    joined = set(self.joined_ranks(
                        getattr(self, "join_round", 0)).keys())
                    if missing and missing <= joined:
                        if kind == "broadcast" and \
                                (my_sig["op"] - KIND_IDS["broadcast"]) in \
                                joined:
                            self._publish(
                                name, epoch,
                                f"broadcast root rank "
                                f"{my_sig['op'] - KIND_IDS['broadcast']} has "
                                f"joined (no data to broadcast)")
                            return
                        self.publish_joinop(name, epoch, my_sig, kind)
                        joinop_published = True
                if now - last_stall_check > 1.0:
                    last_stall_check = now
                    st, report = self.stall.check(now)
                    if st >= 1:
                        for tname, waited, ready, missing in report:
                            get_logger().warning(
                                "Stalled collective %s: waited %.0fs; ready "
                                "ranks %s; missing ranks %s "
                                "(HOROVOD_STALL_CHECK_TIME_SECONDS)",
                                tname.split("#")[0], waited, ready, missing)
                    if st == 2:
                        self._publish(name, epoch,
                                      "stall shutdown threshold exceeded")
                        return
                if now > deadline:
                    self._publish(
                        name, epoch,
                        f"negotiation timed out; arrived={sorted(arrived)}")
                    return
                if len(arrived) < self.size:
                    time.sleep(0.01)
            # Native validation errors embed the epoch-scoped table key;
            # surface the user-facing name instead.
            self._publish(name, epoch,
                          self.msgtable.validate(tbl_key).replace(tbl_key,
                                                                  name))
        finally:
            self.stall.record_done(tbl_key)
            self.msgtable.erase(tbl_key)

    def _publish(self, name: str, epoch: int, err: str) -> None:
        self.client.put(f"negotiate@{self._gen}", f"resp/{name}/{epoch}",
                        json.dumps({"error": err}).encode())

    def _wait_response(self, name: str, resp_key: str,
                       reannounce=None) -> str:
        deadline = time.time() + self._timeout
        last_announce_check = time.time()
        while time.time() < deadline:
            raw = self.client.get(f"negotiate@{self._gen}", resp_key)
            if raw is not None:
                return json.loads(raw).get("error", "")
            now = time.time()
            if reannounce is not None and now - last_announce_check > 0.5:
                last_announce_check = now
                epoch, sig, kind = reannounce
                self._maybe_announce(name, epoch, sig, kind)
            time.sleep(0.005)
        raise HorovodInternalError(
            f"timed out waiting for negotiation verdict on {name!r}")
