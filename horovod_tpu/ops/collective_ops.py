"""Axis-level collective primitives — the TPU-native data plane.

This module is the idiomatic replacement for the reference's entire L1/L2 stack
(horovod/common/ops/{mpi,nccl,gloo,ccl}_operations + fusion-buffer memcpys,
SURVEY.md §2.2): every collective is a pure function over a named mesh axis,
meant to be traced inside ``jax.jit``/``shard_map`` so XLA lowers it directly
onto ICI (and DCN across slices).  There is no fusion buffer here — XLA's
collective combiner plays that role in compiled programs; the explicit fusion
planner survives only on the eager path (ops/eager.py + the C++ core).

Semantics parity (reference symbols cited per function):

* ``allreduce``   — MPI_Allreduce/ncclAllReduce analog; ReduceOp
  {AVERAGE, SUM, ADASUM, MIN, MAX, PRODUCT} from message.h:43 plus
  prescale/postscale factors carried by Request (message.h:59).
* ``allgather``   — concat along axis 0 (collective_operations.h:126).
* ``broadcast``   — root's tensor to all (collective_operations.h:177).
* ``alltoall``    — equal-split axis-0 exchange (collective_operations.h:188);
  uneven splits are an eager-path feature (XLA needs static shapes).
* ``reducescatter`` — psum_scatter; the reference gives the first
  ``dim0 % size`` ranks one extra row (collective_operations.cc
  ComputeOutputShapeForRank) — under SPMD every shard must have equal shape, so
  uneven dim0 is zero-padded; see ``reducescatter_padded_size``.
* gradients: these are ordinary differentiable lax collectives, which yields
  exactly the gradient table the reference registers by hand
  (tensorflow/mpi_ops.py:115-537): allreduce grad = allreduce, allgather grad =
  reduce-scatter slice, broadcast grad = reduce-to-root, alltoall grad =
  inverse alltoall.

Process sets (process_set.h:26) appear here as a static ``members`` tuple of
slot indices.  XLA replica groups (``axis_index_groups``) must form an
equal-size partition of the axis, which arbitrary subsets don't satisfy, so
subset collectives use the *mask* formulation: reduce masked values over the
full axis (non-members contribute the identity element) and restore
non-members' inputs afterwards.  On the torus this costs the same as a
full-axis collective — the right trade on ICI, where partial rings don't beat
the full ring for moderate subset sizes — and it keeps every program total
over the mesh as SPMD requires.  Equal partitions (e.g. hierarchical
node-local groups) can still pass native ``groups``.
"""

from __future__ import annotations

import enum
import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class ReduceOp(enum.IntEnum):
    """Reduction operators (message.h:43 ReduceOp enum, same numbering)."""
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Horovod-API-compatible aliases (horovod.torch exposes these as module attrs).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def _apply_scale(x: jax.Array, factor: float) -> jax.Array:
    if factor == 1.0:
        return x
    # Scale in f32 for low-precision inputs, mirroring the reference's fp16
    # SIMD scale path (collective_operations.h:96-124) which avoids fp16
    # rounding of the scale factor itself.
    if x.dtype in (jnp.bfloat16, jnp.float16):
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    if jnp.issubdtype(x.dtype, jnp.integer):
        return (x * factor).astype(x.dtype)
    return x * factor


def _n_participants(axis_name: str, members) -> int:
    return len(members) if members is not None else lax.axis_size(axis_name)


def _member_mask(members: Sequence[int], axis_name: str):
    idx = lax.axis_index(axis_name)
    return jnp.isin(idx, jnp.asarray(members, dtype=jnp.int32)), idx


def _group_rank(members: Sequence[int], idx):
    """Rank within the member list for the calling slot (members is sorted)."""
    return jnp.searchsorted(jnp.asarray(members, dtype=jnp.int32), idx)


def _identity_for(op: ReduceOp, dtype):
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        return jnp.zeros((), dtype)
    if op == ReduceOp.MIN:
        return (jnp.array(jnp.iinfo(dtype).max, dtype)
                if jnp.issubdtype(dtype, jnp.integer)
                else jnp.array(jnp.inf, dtype))
    if op == ReduceOp.MAX:
        return (jnp.array(jnp.iinfo(dtype).min, dtype)
                if jnp.issubdtype(dtype, jnp.integer)
                else jnp.array(-jnp.inf, dtype))
    if op == ReduceOp.PRODUCT:
        return jnp.ones((), dtype)
    raise ValueError(f"no identity for {op!r}")


def _ring_reduce(x: jax.Array, axis_name: str, op_fn,
                 groups=None) -> jax.Array:
    """Exact elementwise reduction without a gather: rotate copies around
    the (group) ring N-1 times, folding with ``op_fn`` — O(|x|) memory,
    N-1 ICI hops.  The ring neighbor permutation is identical every hop, so
    the loop stays a compact ``fori_loop`` (compiler-friendly control flow,
    no O(N) program blowup).

    Double-buffered schedule (same shape as parallel/ring.py's): the
    hop-(i+1) ``ppermute`` is issued on the already-received buffer BEFORE
    the hop-i fold, so the ICI transfer carries no data dependency on the
    fold and XLA's async collective scheduler can overlap them; the first
    transfer is prefetched ahead of the loop and the last hop folds
    outside it, keeping total transfers at N-1.  Fold order (and therefore
    float bit patterns) is identical to the serial schedule."""
    if groups is None:
        n = lax.axis_size(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]
    else:
        n = len(groups[0])
        perm = [(g[i], g[(i + 1) % n]) for g in groups for i in range(n)]
    if n == 1:
        return x

    first = lax.ppermute(x, axis_name, perm)  # hop-1 data, prefetched

    def body(_, carry):
        acc, cur = carry
        nxt = lax.ppermute(cur, axis_name, perm)  # hop-(i+1) transfer first
        return op_fn(acc, cur), nxt

    acc, last = lax.fori_loop(0, n - 2, body, (x, first))
    return op_fn(acc, last)  # final hop: fold only, nothing left to rotate


def allreduce(x: jax.Array,
              op: ReduceOp = ReduceOp.AVERAGE,
              *,
              axis_name: str = "hvd",
              members: Optional[Tuple[int, ...]] = None,
              groups=None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0) -> jax.Array:
    """Allreduce over the mesh axis (EnqueueTensorAllreduce analog,
    operations.cc:1408, executed as ncclAllReduce in the reference).

    ``members``: static subset of slot indices (a process set); non-member
    slots pass their input through unchanged."""
    x_orig = x
    x = _apply_scale(x, prescale_factor)
    n = _n_participants(axis_name, members)
    masked = x
    if members is not None:
        mask, _ = _member_mask(members, axis_name)
        ident = _identity_for(op if op != ReduceOp.ADASUM else ReduceOp.SUM,
                              x.dtype)
        masked = jnp.where(mask, x, ident)
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        r = lax.psum(masked, axis_name, axis_index_groups=groups)
        if op == ReduceOp.AVERAGE:
            r = r // n if jnp.issubdtype(r.dtype, jnp.integer) else r / n
    elif op == ReduceOp.MIN:
        r = lax.pmin(masked, axis_name, axis_index_groups=groups)
    elif op == ReduceOp.MAX:
        r = lax.pmax(masked, axis_name, axis_index_groups=groups)
    elif op == ReduceOp.PRODUCT:
        # No pprod primitive.  Ring-reduce via ppermute: N-1 hops each
        # multiplying the neighbor's copy — O(|x|) memory and exact for
        # every dtype (an all_gather lowering is O(N·|x|) and blows up for
        # large gradient tensors at pod scale; log-exp psum is inexact).
        # The ring fold order is rotated per rank, so float products can
        # differ by ULPs across ranks; canonicalize by broadcasting one
        # leader's fold (reduce+bcast semantics — every rank gets the
        # bitwise-identical result, the allreduce contract).
        r = _ring_reduce(masked, axis_name, jnp.multiply, groups=groups)
        if jnp.issubdtype(r.dtype, jnp.floating) or \
                jnp.issubdtype(r.dtype, jnp.complexfloating):
            idx = lax.axis_index(axis_name)
            if groups is None:
                leaders = jnp.asarray(
                    [members[0] if members is not None else 0], jnp.int32)
            else:
                leaders = jnp.asarray([g[0] for g in groups], jnp.int32)
            canon = jnp.where(jnp.isin(idx, leaders), r, jnp.zeros_like(r))
            r = lax.psum(canon, axis_name, axis_index_groups=groups)
    elif op == ReduceOp.ADASUM:
        from . import adasum as _adasum
        r = _adasum.adasum_allreduce(x, axis_name=axis_name, members=members)
    else:
        raise ValueError(f"Unsupported reduce op: {op!r}")
    r = _apply_scale(r, postscale_factor)
    if members is not None:
        # Non-members get their ORIGINAL input back — no pre/postscale
        # (Horovod semantics: they never called the op).
        mask, _ = _member_mask(members, axis_name)
        r = jnp.where(mask, r, x_orig.astype(r.dtype))
    return r


def grouped_allreduce(tensors: Sequence[jax.Array],
                      op: ReduceOp = ReduceOp.AVERAGE,
                      *,
                      axis_name: str = "hvd",
                      members: Optional[Tuple[int, ...]] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0) -> List[jax.Array]:
    """All-or-nothing grouped allreduce (EnqueueTensorAllreduces,
    operations.cc grouped variants; GroupTable semantics group_table.h:31).

    Under jit the group contract is trivially satisfied — ops execute in
    program order — and passing the whole list to one ``lax.psum`` lets XLA's
    combiner fuse them into few large ICI transfers (the compiled-path
    equivalent of the 128 MB fusion buffer, operations.cc:519).
    """
    tensors = list(tensors)
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM) and members is None:
        scaled = [_apply_scale(t, prescale_factor) for t in tensors]
        reduced = lax.psum(tuple(scaled), axis_name)
        if op == ReduceOp.AVERAGE:
            n = lax.axis_size(axis_name)
            reduced = tuple(
                (r // n if jnp.issubdtype(r.dtype, jnp.integer) else r / n)
                for r in reduced)
        return [_apply_scale(r, postscale_factor) for r in reduced]
    return [
        allreduce(t, op, axis_name=axis_name, members=members,
                  prescale_factor=prescale_factor,
                  postscale_factor=postscale_factor)
        for t in tensors
    ]


def allgather(x: jax.Array,
              *,
              axis_name: str = "hvd",
              members: Optional[Tuple[int, ...]] = None,
              groups=None) -> jax.Array:
    """Concatenate each participant's tensor along axis 0
    (AllgatherOp, collective_operations.h:126; MPI_Allgatherv in reference).

    SPMD requires equal shapes per participant; ragged dim0 (allgatherv) is
    provided on the eager path via pad-to-max + size side channel
    (SURVEY.md §7 "dynamic shapes").  With ``members``, every slot computes the
    member-only concat (non-members receive it too; the public API layer
    discards it for them — Horovod semantics are that non-members simply don't
    call the op)."""
    if members is None:
        return lax.all_gather(x, axis_name, axis_index_groups=groups,
                              axis=0, tiled=True)
    stacked = lax.all_gather(x, axis_name, axis=0)  # [n, d0, ...]
    sel = stacked[jnp.asarray(members, dtype=jnp.int32)]  # [k, d0, ...]
    return sel.reshape((-1,) + sel.shape[2:])


def grouped_allgather(tensors: Sequence[jax.Array],
                      *,
                      axis_name: str = "hvd",
                      members: Optional[Tuple[int, ...]] = None) -> List[jax.Array]:
    return [allgather(t, axis_name=axis_name, members=members)
            for t in tensors]


def broadcast(x: jax.Array,
              root_rank: int = 0,
              *,
              axis_name: str = "hvd",
              members: Optional[Tuple[int, ...]] = None) -> jax.Array:
    """Root's tensor to every participant (BroadcastOp,
    collective_operations.h:177; ncclBroadcast in reference).

    Implemented as a masked psum — O(|x|) ICI traffic like a native broadcast,
    no gather blow-up.  Its transpose is a masked reduce-to-root, which is
    precisely the gradient the reference registers for broadcast
    (tensorflow/mpi_ops.py broadcast grad).

    With ``members``, ``root_rank`` is the *set-relative* root (the reference's
    process-set-relative root, torch/mpi_ops.py broadcast_ process_set arg) and
    non-members keep their own tensor."""
    idx = lax.axis_index(axis_name)
    root_global = members[root_rank] if members is not None else root_rank
    is_root = idx == root_global
    orig_dtype = x.dtype
    xf = x.astype(jnp.int32) if orig_dtype == jnp.bool_ else x
    masked = jnp.where(is_root, xf, jnp.zeros_like(xf))
    out = lax.psum(masked, axis_name)
    out = out.astype(orig_dtype)
    if members is not None:
        mask, _ = _member_mask(members, axis_name)
        out = jnp.where(mask, out, x)
    return out


def alltoall(x: jax.Array,
             *,
             axis_name: str = "hvd",
             members: Optional[Tuple[int, ...]] = None,
             groups=None) -> jax.Array:
    """Equal-split all-to-all: row block i of my tensor goes to participant i
    (AlltoallOp, collective_operations.h:188).  The uneven ``splits`` variant
    (alltoallv) lives on the eager path.  This is also the Ulysses
    sequence-parallel building block (SURVEY.md §5.8)."""
    n = _n_participants(axis_name, members)
    if x.shape[0] % n != 0:
        raise ValueError(
            f"alltoall requires dim0 ({x.shape[0]}) divisible by group size "
            f"({n}) under jit; use eager alltoall with splits for ragged sends")
    if members is None:
        return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                              axis_index_groups=groups, tiled=True)
    # Subset path: k-1 block rotations around the MEMBER ring (ppermute) —
    # O(|x|) memory, one block per hop.  (The previous full-axis all_gather
    # lowering was O(N·|x|), a blowup for large tensors at pod scale.)  At
    # hop s, member g sends its block (g+s) mod k to member (g+s) mod k and
    # receives block g from member (g-s) mod k; non-members are not in the
    # permutation, so they send nothing and keep their input.
    mask, idx = _member_mask(members, axis_name)
    grank = _group_rank(members, idx)
    blk = x.shape[0] // n
    k = len(members)
    out0 = lax.dynamic_slice_in_dim(x, grank * blk, blk, axis=0)
    parts = [out0]  # block from myself (hop 0)
    for s in range(1, k):
        perm = [(members[i], members[(i + s) % k]) for i in range(k)]
        send_idx = ((grank + s) % k) * blk
        send = lax.dynamic_slice_in_dim(x, send_idx, blk, axis=0)
        parts.append(lax.ppermute(send, axis_name, perm))
    # parts[s] = block received at hop s, i.e. from member (grank - s) mod k;
    # reorder so row-block j comes from member j.
    stacked = jnp.stack(parts)                                # [k, blk, ...]
    src = (grank - jnp.arange(k)) % k                         # hop -> source
    ordered = jnp.zeros_like(stacked).at[src].set(stacked)
    out = ordered.reshape((-1,) + x.shape[1:])                # [k*blk, ...]
    return jnp.where(mask, out, x[:out.shape[0]]) if out.shape == x.shape \
        else out


def reducescatter_padded_size(dim0: int, n: int) -> int:
    """Padded dim0 so every participant's shard is equal.

    The reference hands the first ``dim0 % n`` ranks one extra row
    (collective_operations.cc ComputeOutputShapeForRank); SPMD shards must be
    uniform, so we pad up and let callers slice."""
    return math.ceil(dim0 / n) * n


def reducescatter(x: jax.Array,
                  op: ReduceOp = ReduceOp.SUM,
                  *,
                  axis_name: str = "hvd",
                  members: Optional[Tuple[int, ...]] = None,
                  groups=None,
                  prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0) -> jax.Array:
    """Reduce then scatter row blocks (ReducescatterOp,
    collective_operations.h:271; ncclReduceScatter).  Supports SUM and AVERAGE
    (the reference's reducescatter ReduceOp surface)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("reducescatter supports SUM and AVERAGE")
    n = _n_participants(axis_name, members)
    x = _apply_scale(x, prescale_factor)
    padded = reducescatter_padded_size(x.shape[0], n)
    pad = padded - x.shape[0]
    xp = x
    if pad:
        xp = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], dtype=x.dtype)], axis=0)
    if members is None:
        r = lax.psum_scatter(xp, axis_name, scatter_dimension=0,
                             axis_index_groups=groups, tiled=True)
    else:
        mask, idx = _member_mask(members, axis_name)
        grank = _group_rank(members, idx)
        blk = padded // n
        masked = jnp.where(mask, xp, jnp.zeros_like(xp))
        total = lax.psum(masked, axis_name)                   # [padded, ...]
        start = (jnp.zeros((total.ndim,), jnp.int32)
                 .at[0].set((grank * blk).astype(jnp.int32)))
        r = lax.dynamic_slice(total, tuple(start), (blk,) + x.shape[1:])
    if op == ReduceOp.AVERAGE:
        r = r // n if jnp.issubdtype(r.dtype, jnp.integer) else r / n
    return _apply_scale(r, postscale_factor)


def grouped_reducescatter(tensors: Sequence[jax.Array],
                          op: ReduceOp = ReduceOp.SUM,
                          *,
                          axis_name: str = "hvd",
                          members: Optional[Tuple[int, ...]] = None) -> List[jax.Array]:
    return [reducescatter(t, op, axis_name=axis_name, members=members)
            for t in tensors]


def hierarchical_allreduce(x: jax.Array,
                           op: ReduceOp = ReduceOp.SUM,
                           *,
                           axis_name: str = "hvd",
                           local_size: int,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0) -> jax.Array:
    """Two-level allreduce: reduce-scatter within each node's chips, reduce
    across nodes, allgather back within nodes.

    This is the ICI/DCN-native form of the reference's
    NCCLHierarchicalAllreduce (nccl_operations.h:231: NCCL ReduceScatter
    intra-node → MPI allreduce across node leaders → NCCL Allgather) and
    NCCLTorusAllreduce (nccl_operations.h:253: local/cross communicator
    decomposition), selected by HOROVOD_HIERARCHICAL_ALLREDUCE /
    HOROVOD_TORUS_ALLREDUCE.  On TPU the intra-node phase rides ICI and the
    cross phase rides DCN; both phases use *equal-size* replica groups,
    which XLA lowers natively.

    Requires a homogeneous layout (axis size divisible by ``local_size``)
    and a node-major mesh order (slots [k*L, (k+1)*L) on node k — the
    default Mesh construction order).  Numerics identical to flat psum.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("hierarchical_allreduce supports SUM and AVERAGE")
    n = lax.axis_size(axis_name)
    if n % local_size != 0:
        raise ValueError(
            f"axis size {n} not divisible by local_size {local_size} "
            f"(hierarchical allreduce needs a homogeneous layout)")
    cross = n // local_size
    if local_size == 1 or cross == 1:
        return allreduce(x, op, axis_name=axis_name,
                         prescale_factor=prescale_factor,
                         postscale_factor=postscale_factor)
    local_groups = [[k * local_size + j for j in range(local_size)]
                    for k in range(cross)]
    cross_groups = [[j + k * local_size for k in range(cross)]
                    for j in range(local_size)]
    x = _apply_scale(x, prescale_factor)
    orig_shape = x.shape
    flat = x.reshape(-1)
    pad = reducescatter_padded_size(flat.shape[0], local_size) - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Phase 1: reduce-scatter inside the node (each chip owns a chunk).
    chunk = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                             axis_index_groups=local_groups, tiled=True)
    # Phase 2: allreduce the homogeneous chunk across nodes (same-local-rank
    # chips form a cross group — the reference's "cross communicator").
    # Expressed as grouped all_gather + row-sum: equivalent to a grouped
    # psum, and supported by every backend (the CPU emulation backend lacks
    # grouped psum lowering); XLA fuses the reduction.
    gathered = lax.all_gather(chunk, axis_name,
                              axis_index_groups=cross_groups, axis=0)
    chunk = jnp.sum(gathered, axis=0).astype(chunk.dtype)
    # Phase 3: allgather chunks back inside the node.
    full = lax.all_gather(chunk, axis_name, axis_index_groups=local_groups,
                          axis=0, tiled=True)
    if pad:
        full = full[:-pad]
    r = full.reshape(orig_shape)
    if op == ReduceOp.AVERAGE:
        r = r // n if jnp.issubdtype(r.dtype, jnp.integer) else r / n
    return _apply_scale(r, postscale_factor)


def barrier(*, axis_name: str = "hvd") -> jax.Array:
    """Synchronization barrier (BarrierOp, collective_operations.h:335).
    In a compiled program this is a collective the schedule cannot reorder
    across; eagerly, ops/eager.py blocks on the result."""
    return lax.psum(jnp.zeros((), dtype=jnp.int32), axis_name)
