"""Horovod-Timeline-compatible Chrome-trace profiler.

Reference: horovod/common/timeline.{h,cc} — rank 0 writes a Chrome trace JSON
(``HOROVOD_TIMELINE=/path`` or the ``horovod_start_timeline`` runtime API,
operations.cc:1077).  Each tensor gets a lifecycle: NEGOTIATE_<OP> instant
events as ranks' requests arrive, then a top-level op state, then nested
*activities* named by the executing op (QUEUE, WAIT_FOR_DATA,
MEMCPY_IN_FUSION_BUFFER, NCCL_ALLREDUCE..., macros common.h:80-114).  Events
flow through a lock-free SPSC queue to a dedicated writer thread
(timeline.h:84-92) so the hot path never blocks on IO.

TPU build: the host-side lifecycle is identical (NEGOTIATE → op → activities
like QUEUE / TRACE_CACHE / XLA_EXECUTE); the *device* plane is covered by
``jax.profiler`` traces which a user can overlay — XLA programs time their own
collectives, the host runtime cannot see inside them (SURVEY.md §5.1).
Events go through a queue.Queue to a writer thread; the file is valid
Chrome-trace JSON (array form, openable in chrome://tracing / Perfetto).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Activity names preserved from the reference (common.h:80-114).
QUEUE = "QUEUE"
WAIT_FOR_DATA = "WAIT_FOR_DATA"
WAIT_FOR_OTHER_TENSOR_DATA = "WAIT_FOR_OTHER_TENSOR_DATA"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
XLA_EXECUTE = "XLA_EXECUTE"
TRACE_CACHE_HIT = "TRACE_CACHE_HIT"
TRACE_COMPILE = "TRACE_COMPILE"

# Ring-collective hop events (no reference analog — the reference has no
# ring/sequence parallelism).  RING_HOP carries the traced hop schedule
# (parallel/ring.py set_ring_timeline); RING_KERNEL / RING_TRANSFER carry
# measured per-hop spans (bench.py ring microbench) so kernel time and ICI
# transfer time are separable in the trace viewer.
RING_HOP = "RING_HOP"
RING_KERNEL = "RING_KERNEL"
RING_TRANSFER = "RING_TRANSFER"

# Serving-plane counters (no reference analog — the reference is
# training-only).  serve/metrics.py publishes engine statistics (tokens,
# batch occupancy, queue depth, latency quantiles) as counter events under
# SERVE/<component> so a serving run's trace charts them next to any
# training-side op lifecycle in the same viewer.
SERVE = "SERVE"

# Fault-injection firings (faultline/plan.py): every fault a FaultPlan
# fires is an instant event under FAULTLINE/<kind>, so a chaos run's
# trace shows exactly what broke, where (injection point + instance),
# and at which step index — the reproducibility artifact two same-seed
# runs must agree on (docs/fault_injection.md).
FAULTLINE = "FAULTLINE"

# Brownout rung transitions (serve/controller.py ladder): every rung
# change the fleet controller walks is an instant event under
# BROWNOUT/<direction>, so a soak's trace shows exactly when the fleet
# started degrading, how deep it went, and when it recovered — next to
# the FAULTLINE instants that caused it.
BROWNOUT = "BROWNOUT"

# Live weight hot-swap transitions (serve/registry.py roll): every
# per-replica phase of a rollout — drain, swap, alive, abort — is an
# instant event under SWAP/<model>, so a trace shows the replica-by-
# replica walk of a roll next to the replica death/revival events it
# rides on, and exactly where an aborted roll stopped.
SWAP = "SWAP"

# Lock-witness findings (analysis/witness.py, HVD_SANITIZE=1): every
# observed lock-order inversion / naked wait is an instant event under
# WITNESS/<rule>, so a sanitized run's trace shows the near-deadlock at
# the moment it happened, next to the serve/fault events.
WITNESS = "WITNESS"

# Static per-step collective census (no reference analog — the reference
# only learns the collective set at runtime through negotiation; on TPU
# the jaxpr checker reads it off the traced program, analysis/
# jaxpr_check.py).  Rendered as Chrome-trace counter events so the
# viewer charts collective count/bytes per primitive next to the op
# lifecycle.
COLLECTIVE_CENSUS = "COLLECTIVE_CENSUS"

# Static per-step MEMORY census (hvdmem, analysis/memplan.py): the
# jaxpr liveness walk's peak-live-bytes estimate and per-primitive
# allocation breakdown, plus the serve engine's pool-budget plan
# (pool + weights vs HVD_MEM_BUDGET_BYTES).  Rendered as counter
# events so the viewer charts the footprint a program was PLANNED to
# have next to what the op lifecycle actually did with it.
MEMORY_CENSUS = "MEMORY_CENSUS"

# Static per-step COMMUNICATION census (hvdshard, analysis/
# shardplan.py): per-collective wire bytes (payload x communicator
# group size), the ICI vs DCN fabric split per mesh axis, implicit-
# reshard bytes (HVD400), and the comm-budget headrooms
# (HVD_COMM_BUDGET_BYTES / HVD_COMM_DCN_BUDGET_BYTES).  Rendered as
# counter events so the viewer charts what a step was PLANNED to move
# over each fabric next to the op lifecycle that moved it.
COMM_CENSUS = "COMM_CENSUS"

# Elastic world transitions (elastic/__init__.py): instant events
# around the scale-down/scale-up barriers — reset entered (old world
# still up), world adopted (new world initialized) — so a wedged or
# flaky resize leaves a post-mortem trail of WHICH barrier the stall
# sat in and which world versions were involved.
ELASTIC = "ELASTIC"

# Distributed request tracing (obs/tracing.py, docs/observability.md):
# per-request spans render as Chrome ASYNC events ("b"/"e") keyed by the
# request's trace_id, so one /generate call's http-handle → route →
# queue-wait → prefill → decode lifecycle nests in its own lane next to
# the training-op lifecycle, FAULTLINE instants, and SERVE counters.
# Per-decode-iteration progress renders as FLOW events ("s"/"t"/"f")
# under the same id — Perfetto draws the token stream as arrows through
# the request's spans.
HVDTRACE = "hvdtrace"
HVDTRACE_FLOW = "hvdtrace-flow"


def force_put_sentinel(q: "queue.Queue", on_drop) -> None:
    """Deliver a ``None`` shutdown sentinel to a bounded queue WITHOUT
    blocking: the producer side must already be closed (no new puts),
    so if the queue is full, discard queued items — accounting each via
    ``on_drop()``, they will never be written — until the sentinel
    fits.  Shared by the Timeline and Tracer writer shutdown paths: a
    silently-lost sentinel leaves a healthy writer parked in ``get()``
    forever."""
    while True:
        try:
            q.put_nowait(None)
            return
        except queue.Full:
            try:
                q.get_nowait()
                on_drop()
            except queue.Empty:
                continue


class Timeline:
    """Chrome-trace writer with a background writer thread
    (TimelineWriter, timeline.h:48)."""

    def __init__(self, path: str, mark_cycles: bool = False, rank: int = 0,
                 queue_cap: Optional[int] = None):
        self.path = path
        self.mark_cycles = mark_cycles
        self.rank = rank
        # BOUNDED event queue (HVD_TIMELINE_QUEUE_CAP): a stalled writer
        # thread (wedged disk, dead NFS mount) must cost bounded memory —
        # the hot path drops events past the cap rather than queueing
        # unbounded, and every drop is COUNTED so a truncated trace is
        # never mistaken for a complete one (the total surfaces as a
        # counter event at close and as
        # ``hvd_timeline_dropped_events_total`` on serve /metrics).
        cap = queue_cap if queue_cap is not None else int(
            os.environ.get("HVD_TIMELINE_QUEUE_CAP", str(1 << 16)))
        self._queue: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=max(cap, 2))
        self._dropped = 0
        self._drop_lock = threading.Lock()
        self._start = time.monotonic_ns()
        self._closed = False
        self._fh = open(path, "w")
        self._fh.write("[\n")
        self._first = True
        self._writer = threading.Thread(target=self._drain, daemon=True,
                                        name="hvd-timeline-writer")
        self._writer.start()
        self._emit_meta()

    # -- event api ----------------------------------------------------------

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start) / 1e3

    def ts_of(self, mono_ns: int) -> float:
        """Map a caller-captured ``time.monotonic_ns()`` stamp onto this
        timeline's microsecond axis (retroactive span emission: the
        tracer records span boundaries where they happen and emits the
        whole span at its end)."""
        return (mono_ns - self._start) / 1e3

    @property
    def dropped_events(self) -> int:
        """Events dropped at the bounded queue so far (module doc)."""
        with self._drop_lock:
            return self._dropped

    def _put(self, ev: dict) -> None:
        if self._closed:
            return
        try:
            self._queue.put_nowait(ev)
        except queue.Full:
            # Drop rather than stall the hot path (reference SPSC
            # behavior) — but ACCOUNT the drop (class doc).
            with self._drop_lock:
                self._dropped += 1

    def _emit_meta(self):
        self._put({"name": "process_name", "ph": "M", "pid": self.rank,
                   "args": {"name": f"horovod_tpu rank {self.rank}"}})

    def negotiate_start(self, tensor_name: str, op_type: str):
        """NEGOTIATE_<OP> phase begin (timeline.cc NegotiateStart)."""
        self._put({"name": f"NEGOTIATE_{op_type}", "ph": "B",
                   "ts": self._ts_us(), "pid": self.rank, "tid": tensor_name})

    def negotiate_rank_ready(self, tensor_name: str, req_rank: int):
        """Instant event per rank whose request arrived (timeline.cc
        NegotiateRankReady)."""
        self._put({"name": str(req_rank), "ph": "i", "s": "t",
                   "ts": self._ts_us(), "pid": self.rank, "tid": tensor_name})

    def negotiate_end(self, tensor_name: str, op_type: str):
        self._put({"name": f"NEGOTIATE_{op_type}", "ph": "E",
                   "ts": self._ts_us(), "pid": self.rank, "tid": tensor_name})

    def start(self, tensor_name: str, op_type: str):
        """Top-level op state begin (timeline.cc Start)."""
        self._put({"name": op_type, "ph": "B", "ts": self._ts_us(),
                   "pid": self.rank, "tid": tensor_name})

    def activity_start(self, tensor_name: str, activity: str):
        self._put({"name": activity, "ph": "B", "ts": self._ts_us(),
                   "pid": self.rank, "tid": tensor_name})

    def activity_end(self, tensor_name: str, activity: str):
        self._put({"name": activity, "ph": "E", "ts": self._ts_us(),
                   "pid": self.rank, "tid": tensor_name})

    def end(self, tensor_name: str, op_type: str):
        self._put({"name": op_type, "ph": "E", "ts": self._ts_us(),
                   "pid": self.rank, "tid": tensor_name})

    def ring_hop(self, tensor_name: str, hop: int, *, bytes_rotated: int,
                 mask: str = "none", schedule: str = "overlap",
                 skipped_shards: int = 0, dur_us: float = 0.0):
        """One ring-collective hop of the traced schedule (complete-event
        form): hop index, K/V bytes rotated over ICI that hop, the mask
        rule, the hop schedule, and how many shards take the true-skip arm
        instead of running a kernel.  Emitted at TRACE time by
        parallel/ring.py when a timeline is registered via
        ``set_ring_timeline`` — the device plane inside jit is invisible to
        the host (module docstring), so these document the schedule, while
        ``ring_span`` carries measured spans."""
        self._put({"name": f"{RING_HOP}_{hop}", "ph": "X",
                   "ts": self._ts_us(), "dur": dur_us,
                   "pid": self.rank, "tid": tensor_name,
                   "args": {"hop": hop, "bytes_rotated": bytes_rotated,
                            "mask": mask, "schedule": schedule,
                            "skipped_shards": skipped_shards}})

    def ring_span(self, tensor_name: str, hop: int, kind: str,
                  start_us: float, dur_us: float, **args):
        """Measured span for one ring hop: ``kind`` is RING_KERNEL (per-hop
        attention/fold compute) or RING_TRANSFER (the K/V ppermute).  Used
        by the bench ring microbench, which times single-hop programs to
        attribute step time to kernel vs transfer."""
        self._put({"name": f"{kind}_{hop}", "ph": "X", "ts": start_us,
                   "dur": dur_us, "pid": self.rank, "tid": tensor_name,
                   "args": dict(args, hop=hop)})

    def collective_census(self, step_name: str, census: dict):
        """Per-step collective census from the jaxpr checker
        (HVD_ANALYZE=1, analysis/hook.py): ``census`` maps primitive name
        → {"count", "bytes"}.  One counter event per primitive —
        count/bytes chart as stacked counters in the trace viewer."""
        for prim in sorted(census):
            info = census[prim]
            self._put({"name": f"{COLLECTIVE_CENSUS}/{step_name}/{prim}",
                       "ph": "C", "ts": self._ts_us(), "pid": self.rank,
                       "args": {"count": int(info.get("count", 0)),
                                "bytes": int(info.get("bytes", 0))}})

    def memory_census(self, step_name: str, mem: dict):
        """Per-program memory census from the hvdmem liveness walk
        (HVD_ANALYZE=1, analysis/memplan.py): one totals counter (peak /
        input / output / budget-headroom bytes) plus one counter per
        allocating primitive, mirroring ``collective_census``."""
        totals = {"peak_live_bytes": int(mem.get("peak_live_bytes", 0)),
                  "input_bytes": int(mem.get("input_bytes", 0)),
                  "output_bytes": int(mem.get("output_bytes", 0))}
        if mem.get("headroom_bytes") is not None:
            totals["headroom_bytes"] = int(mem["headroom_bytes"])
        self._put({"name": f"{MEMORY_CENSUS}/{step_name}", "ph": "C",
                   "ts": self._ts_us(), "pid": self.rank, "args": totals})
        by_prim = mem.get("by_primitive") or {}
        for prim in sorted(by_prim):
            info = by_prim[prim]
            self._put({"name": f"{MEMORY_CENSUS}/{step_name}/{prim}",
                       "ph": "C", "ts": self._ts_us(), "pid": self.rank,
                       "args": {"count": int(info.get("count", 0)),
                                "bytes": int(info.get("bytes", 0))}})

    def comm_census(self, step_name: str, comm: dict):
        """Per-program communication census from the hvdshard walk
        (HVD_ANALYZE=1, analysis/shardplan.py): one totals counter
        (total/DCN wire bytes, reshard bytes, budget headrooms), one
        counter per collective primitive, and one per mesh axis with
        its ICI/DCN fabric — mirroring ``memory_census``."""
        totals = {"total_wire_bytes": int(comm.get("total_wire_bytes", 0)),
                  "dcn_wire_bytes": int(comm.get("dcn_wire_bytes", 0)),
                  "reshard_bytes": int(comm.get("reshard_bytes", 0))}
        if comm.get("headroom_bytes") is not None:
            totals["headroom_bytes"] = int(comm["headroom_bytes"])
        if comm.get("dcn_headroom_bytes") is not None:
            totals["dcn_headroom_bytes"] = int(comm["dcn_headroom_bytes"])
        self._put({"name": f"{COMM_CENSUS}/{step_name}", "ph": "C",
                   "ts": self._ts_us(), "pid": self.rank, "args": totals})
        by_prim = comm.get("by_primitive") or {}
        for prim in sorted(by_prim):
            info = by_prim[prim]
            self._put({"name": f"{COMM_CENSUS}/{step_name}/{prim}",
                       "ph": "C", "ts": self._ts_us(), "pid": self.rank,
                       "args": {"count": int(info.get("count", 0)),
                                "bytes": int(info.get("bytes", 0)),
                                "wire_bytes":
                                    int(info.get("wire_bytes", 0)),
                                "dcn_bytes":
                                    int(info.get("dcn_bytes", 0))}})
        by_axis = comm.get("by_axis") or {}
        for axis in sorted(by_axis):
            info = by_axis[axis]
            self._put({"name":
                       f"{COMM_CENSUS}/{step_name}/axis/{axis}"
                       f"[{info.get('fabric', 'ici')}]",
                       "ph": "C", "ts": self._ts_us(), "pid": self.rank,
                       "args": {"count": int(info.get("count", 0)),
                                "wire_bytes":
                                    int(info.get("wire_bytes", 0)),
                                "size": int(info.get("size", 1))}})

    def elastic_event(self, phase: str, version: int, detail: str = ""):
        """One elastic world transition (elastic/__init__.py):
        process-scoped instant event carrying the phase (``reset`` when
        the old world starts tearing down, ``world`` when the new one is
        adopted) and the world version — the post-mortem breadcrumbs a
        flaky scale-down/scale-up run leaves around its barriers."""
        self._put({"name": f"{ELASTIC}/{phase}", "ph": "i", "s": "p",
                   "ts": self._ts_us(), "pid": self.rank, "tid": "elastic",
                   "args": {"world_version": int(version),
                            "detail": detail}})

    def serve_counter(self, component: str, values: dict):
        """Serving-engine counter sample (serve/metrics.py): ``values``
        maps statistic name → number.  One counter event per sample —
        occupancy/queue/token counters chart as stacked series in the
        trace viewer under SERVE/<component>."""
        self._put({"name": f"{SERVE}/{component}", "ph": "C",
                   "ts": self._ts_us(), "pid": self.rank,
                   "args": {k: (float(v) if isinstance(v, float) else int(v))
                            for k, v in values.items()}})

    def fault_event(self, kind: str, point: str, instance: str,
                    step: int, trace_id: Optional[str] = None):
        """One fault firing (faultline): process-scoped instant event
        carrying the injection point, instance, and step index — plus
        the request trace_id when the fault fired inside a traced
        request scope (obs/tracing.py), so a chaos run's trace shows
        WHICH request each fault hit."""
        args = {"point": point, "instance": instance, "step": int(step)}
        if trace_id is not None:
            args["trace_id"] = trace_id
        self._put({"name": f"{FAULTLINE}/{kind}", "ph": "i", "s": "p",
                   "ts": self._ts_us(), "pid": self.rank, "tid": point,
                   "args": args})

    def brownout_event(self, direction: str, level: int,
                       rung: str = ""):
        """One brownout rung transition (serve/controller.py):
        process-scoped instant event carrying the walk direction
        (``up``/``down``), the rung now in effect, and its description
        — the trace-side record of WHEN the fleet degraded gracefully
        and when it recovered."""
        self._put({"name": f"{BROWNOUT}/{direction}", "ph": "i",
                   "s": "p", "ts": self._ts_us(), "pid": self.rank,
                   "tid": "hvdctl",
                   "args": {"level": int(level), "rung": rung}})

    def swap_event(self, model: str, replica: str, phase: str,
                   version: int):
        """One hot-swap phase transition (serve/registry.py roll):
        process-scoped instant event carrying the replica being walked,
        the phase (``drain``/``swap``/``alive``/``abort``), and the
        target version — the trace-side record of a live rollout's
        replica-by-replica progress."""
        self._put({"name": f"{SWAP}/{model}", "ph": "i", "s": "p",
                   "ts": self._ts_us(), "pid": self.rank,
                   "tid": "hvdswap",
                   "args": {"replica": replica, "phase": phase,
                            "version": int(version)}})

    def witness_event(self, rule: str, site_path: str, site_line: int,
                      thread_name: str):
        """One lock-witness finding (analysis/witness.py HVD210/HVD211):
        process-scoped instant event carrying the violating acquisition
        site and the thread that performed it."""
        self._put({"name": f"{WITNESS}/{rule}", "ph": "i", "s": "p",
                   "ts": self._ts_us(), "pid": self.rank,
                   "tid": thread_name,
                   "args": {"site": f"{site_path}:{int(site_line)}",
                            "thread": thread_name}})

    def trace_span(self, trace_id: str, name: str, tid: str,
                   start_mono_ns: int, dur_us: float,
                   args: Optional[dict] = None):
        """One request-trace span (obs/tracing.py): Chrome ASYNC begin/end
        pair keyed by the request's trace_id, so every span of one
        request nests in one lane across components.  ``start_mono_ns``
        is a caller-captured ``time.monotonic_ns()`` stamp (spans are
        emitted retroactively at their end)."""
        ts = self.ts_of(start_mono_ns)
        base = {"cat": HVDTRACE, "id": trace_id, "name": name,
                "pid": self.rank, "tid": tid}
        self._put(dict(base, ph="b", ts=ts, args=args or {}))
        self._put(dict(base, ph="e", ts=ts + max(dur_us, 0.0)))

    def trace_flow(self, trace_id: str, name: str, tid: str, phase: str,
                   mono_ns: Optional[int] = None):
        """One request-trace flow event (``phase`` in s/t/f): the
        per-decode-iteration token stream renders as arrows through the
        request's spans in Perfetto."""
        ts = self.ts_of(mono_ns) if mono_ns is not None else self._ts_us()
        ev = {"cat": HVDTRACE_FLOW, "id": trace_id, "name": name,
              "ph": phase, "ts": ts, "pid": self.rank, "tid": tid}
        if phase == "f":
            ev["bp"] = "e"  # bind to the enclosing slice's end
        self._put(ev)

    def trace_instant(self, trace_id: str, name: str, tid: str,
                      args: Optional[dict] = None,
                      mono_ns: Optional[int] = None):
        """Request-scoped instant event (deadline expiry, resubmission,
        preemption) carrying the trace_id in its args."""
        ts = self.ts_of(mono_ns) if mono_ns is not None else self._ts_us()
        self._put({"name": f"{HVDTRACE}/{name}", "ph": "i", "s": "p",
                   "ts": ts, "pid": self.rank, "tid": tid,
                   "args": dict(args or {}, trace_id=trace_id)})

    def mark_cycle(self):
        """Optional cycle marker (HOROVOD_TIMELINE_MARK_CYCLES,
        timeline.cc MarkCycle)."""
        if self.mark_cycles:
            self._put({"name": "CYCLE", "ph": "i", "s": "g",
                       "ts": self._ts_us(), "pid": self.rank, "tid": "cycles"})

    class _Activity:
        def __init__(self, tl, name, activity):
            self.tl, self.name, self.activity = tl, name, activity

        def __enter__(self):
            self.tl.activity_start(self.name, self.activity)
            return self

        def __exit__(self, *exc):
            self.tl.activity_end(self.name, self.activity)
            return False

    def activity(self, tensor_name: str, activity: str) -> "_Activity":
        return self._Activity(self, tensor_name, activity)

    # -- writer thread ------------------------------------------------------

    def _drain(self):
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            line = json.dumps(ev)
            if not self._first:
                self._fh.write(",\n")
            self._first = False
            self._fh.write(line)

    def close(self):
        if self._closed:
            return
        self._closed = True

        def count_drop():
            with self._drop_lock:
                self._dropped += 1
        force_put_sentinel(self._queue, count_drop)
        self._writer.join(timeout=5)
        if self._writer.is_alive():
            # Writer wedged mid-write (dead disk): appending the trailer
            # from this thread would interleave with its writes and
            # closing the handle would crash it — abandon the file; the
            # daemon thread dies with the process.
            return
        with self._drop_lock:
            dropped = self._dropped
        # Drop accounting belongs IN the artifact: a trace missing events
        # must say so.  The writer has exited, so the trailer writes go
        # straight to the file handle.
        line = json.dumps({"name": "hvd_timeline_dropped_events_total",
                           "ph": "C", "ts": self._ts_us(),
                           "pid": self.rank,
                           "args": {"dropped": dropped}})
        if not self._first:
            self._fh.write(",\n")
        self._first = False
        self._fh.write(line)
        self._fh.write("\n]\n")
        self._fh.flush()
        self._fh.close()
