"""Parameter/object broadcast + allgather helpers.

Reference: horovod/tensorflow/functions.py (broadcast_variables,
broadcast_object, broadcast_object_fn, allgather_object — pickled objects
shipped as uint8 tensors with a size side-channel) and
horovod/torch/functions.py (broadcast_parameters,
broadcast_optimizer_state).  These are the checkpoint/startup
synchronization standard: rank 0 restores, everyone else receives
(SURVEY.md §5.4).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import core as _core
from . import ops as _ops
from .process_sets import ProcessSet, global_process_set


def broadcast_variables(params, root_rank: int = 0,
                        process_set: ProcessSet = global_process_set):
    """Broadcast a pytree of arrays from ``root_rank``
    (tensorflow/functions.py broadcast_variables; torch
    broadcast_parameters).  Works in-trace or eagerly."""
    # stacked=False: parameters are replicated values, never per-rank stacks —
    # prevents the leading-dim heuristic from shredding a weight whose first
    # dim equals the emulated rank count.
    return jax.tree_util.tree_map(
        lambda t: _ops.broadcast(t, root_rank=root_rank,
                                 process_set=process_set, stacked=False),
        params)


# Horovod torch spelling.
broadcast_parameters = broadcast_variables


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: ProcessSet = global_process_set):
    """Broadcast optimizer state (torch/functions.py
    broadcast_optimizer_state).  optax states are pytrees of arrays +
    static leaves; only array leaves are broadcast."""
    def bc(leaf):
        if isinstance(leaf, (jax.Array, np.ndarray)) or jnp.isscalar(leaf):
            arr = jnp.asarray(leaf)
            if arr.dtype == jnp.int32 and arr.ndim == 0:
                # step counters etc. — broadcast as arrays too
                pass
            return _ops.broadcast(arr, root_rank=root_rank,
                                  process_set=process_set, stacked=False)
        return leaf

    return jax.tree_util.tree_map(bc, opt_state)


def _obj_to_u8(obj: Any) -> np.ndarray:
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()


def _u8_to_obj(arr: np.ndarray) -> Any:
    return pickle.load(io.BytesIO(arr.tobytes()))


def broadcast_object(obj: Any = None, root_rank: int = 0,
                     name: Optional[str] = None,
                     process_set: ProcessSet = global_process_set) -> Any:
    """Pickle-broadcast an arbitrary Python object from root
    (tensorflow/functions.py broadcast_object: object → uint8 tensor, size
    broadcast first, then payload).

    Emulated/single-rank modes return the object as-is (there is one Python
    process — every "rank" already shares it).  Multi-process mode performs
    the real size + payload broadcasts."""
    topo = _core._require_init().topology
    if topo.size == 1 or topo.emulated:
        return obj
    rank = _core.rank()
    payload = _obj_to_u8(obj) if rank == root_rank else np.zeros(0, np.uint8)
    sz = jnp.asarray([payload.size], jnp.int32)
    sz = np.asarray(_ops.broadcast(sz, root_rank=root_rank,
                                   process_set=process_set))
    n = int(sz[0])
    buf = np.zeros(n, np.uint8)
    buf[:payload.size] = payload[:n] if rank == root_rank else 0
    out = np.asarray(_ops.broadcast(jnp.asarray(buf), root_rank=root_rank,
                                    process_set=process_set,
                                    name=name))
    return _u8_to_obj(out)


def broadcast_object_fn(root_rank: int = 0, name: Optional[str] = None,
                        process_set: ProcessSet = global_process_set):
    """Returns a function broadcasting objects from root
    (tensorflow/functions.py broadcast_object_fn)."""
    def fn(obj=None):
        return broadcast_object(obj, root_rank=root_rank, name=name,
                                process_set=process_set)
    return fn


def allgather_object(obj: Any, name: Optional[str] = None,
                     process_set: ProcessSet = global_process_set) -> list:
    """Gather a Python object from every rank → list ordered by rank
    (tensorflow/functions.py allgather_object: pickled uint8 + ragged
    allgather).

    Emulated/single-rank: the caller holds all "ranks'" objects — pass a list
    of per-rank objects (emulated) or any object (single rank)."""
    topo = _core._require_init().topology
    if topo.size == 1:
        return [obj]
    if topo.emulated:
        if not isinstance(obj, (list, tuple)) or len(obj) != topo.size:
            raise ValueError(
                f"emulated allgather_object takes a list of {topo.size} "
                f"per-rank objects")
        return list(obj)
    payload = _obj_to_u8(obj)
    out = _ops.allgather(jnp.asarray(payload)[:, None].astype(jnp.uint8),
                         name=name, process_set=process_set)
    # Ragged path returns the concatenation; we need per-rank boundaries.
    sizes = np.asarray(_ops.allgather(
        jnp.asarray([[payload.size]], jnp.int64), process_set=process_set)
    ).ravel()
    flat = np.asarray(out).ravel()
    objs, off = [], 0
    for s in sizes:
        objs.append(_u8_to_obj(flat[off:off + int(s)]))
        off += int(s)
    return objs
