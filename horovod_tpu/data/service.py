"""Data service: host processes serve preprocessed batches to trainers.

Reference: the Horovod-managed ``tf.data.experimental.service`` cluster
(runner/common/service/compute_service.py:99 ComputeService — an RPC
registry of dispatchers and workers with registration waits and shutdown
propagation — plus tensorflow/data/compute_service.py's send/read sides).
TPU analog: dedicated CPU-heavy hosts run ``serve_dataset`` (a batch
producer + HTTP endpoint), and each trainer iterates ``RemoteDataset``,
which round-robins pickled batches across the registered producers —
decoupling input preprocessing from accelerator hosts the way the
reference's data service does.

Registry semantics (round 5, the ComputeService contract this module
implements over the rendezvous KV instead of an RPC service):

* producers REGISTER with a heartbeat — the record carries
  ``{addr, ts}`` and a daemon refreshes ``ts`` every
  ``HEARTBEAT_S``; ``stop()`` deregisters explicitly (graceful), a
  crashed producer just stops heartbeating;
* consumers discover producers FROM THE REGISTRY each sweep, so
  late-joining producers are picked up mid-epoch (the reference's
  WaitForDispatcherRegistration shape without the fixed-id slots);
* DEAD-PRODUCER EVICTION: a connection failure to a producer whose
  heartbeat is stale (older than ``alive_window_s``) evicts it — the
  trainer completes the epoch from the survivors (its undelivered
  batches are lost, exactly the reference's at-most-once data-service
  delivery); a failure with a FRESH heartbeat is treated as transient
  and retried.
"""

from __future__ import annotations

import json
import pickle
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterable, Iterator, List, Optional

from ..runner.http_server import KVStoreClient
from ..utils import get_logger

REGISTRY_SCOPE = "dataservice"

#: Producer heartbeat period / consumer liveness window.
HEARTBEAT_S = 2.0
ALIVE_WINDOW_S = 10.0


class _BatchHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        if self.path != "/next":
            self.send_response(404)
            self.end_headers()
            return
        try:
            payload = self.server.batch_queue.get(timeout=30)
        except queue.Empty:
            # The None sentinel is consumed exactly once, so every reader
            # after the first must learn of exhaustion from the flag —
            # otherwise a second trainer retries 204s forever.
            code = 410 if self.server.exhausted else 204
            self.send_response(code)
            self.end_headers()
            return
        if payload is None:
            self.server.exhausted = True
            self.send_response(410)  # Gone: dataset exhausted
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class DataServiceWorker:
    """One producer endpoint (the reference's data-service *worker*): pulls
    batches from an iterable on a background thread, serves them over HTTP,
    registers itself — with a heartbeat — in the rendezvous KV store."""

    def __init__(self, dataset: Iterable[Any], worker_id: int = 0,
                 rendezvous_addr: Optional[str] = None,
                 rendezvous_port: Optional[int] = None,
                 queue_size: int = 8,
                 heartbeat_s: float = HEARTBEAT_S):
        self.dataset = dataset
        self.worker_id = worker_id
        self._rdv = (rendezvous_addr, rendezvous_port)
        self._queue_size = queue_size
        self._heartbeat_s = heartbeat_s
        self._stop_hb = threading.Event()
        self.httpd: Optional[ThreadingHTTPServer] = None

    def start(self) -> int:
        self.httpd = ThreadingHTTPServer(("0.0.0.0", 0), _BatchHandler)
        self.httpd.batch_queue = queue.Queue(maxsize=self._queue_size)
        self.httpd.exhausted = False
        port = self.httpd.server_address[1]

        def produce():
            # Sentinel goes out even if the dataset iterable raises, so
            # readers see 410 (exhausted) rather than polling 204 forever.
            try:
                for item in self.dataset:
                    self.httpd.batch_queue.put(pickle.dumps(item))
            finally:
                self.httpd.batch_queue.put(None)

        threading.Thread(target=produce, daemon=True,
                         name="hvd-data-producer").start()
        threading.Thread(target=self.httpd.serve_forever, daemon=True,
                         name="hvd-data-server").start()
        addr, rport = self._rdv
        if addr and rport:
            import socket
            my = socket.gethostbyname(socket.gethostname())
            client = KVStoreClient(addr, int(rport))
            key = f"worker/{self.worker_id}"
            endpoint = f"{my}:{port}"

            def put_record():
                client.put(REGISTRY_SCOPE, key, json.dumps(
                    {"addr": endpoint, "ts": time.time()}).encode())

            put_record()  # registration IS the first heartbeat (sync, so
            # a consumer starting right after serve_dataset() returns
            # already sees this producer)

            def hb_loop():
                while not self._stop_hb.wait(self._heartbeat_s):
                    try:
                        put_record()
                    except Exception as e:
                        get_logger().debug(
                            "data-service heartbeat failed: %s", e)

            threading.Thread(target=hb_loop, daemon=True,
                             name=f"hvd-data-hb-{self.worker_id}").start()
        return port

    def stop(self):
        """Graceful shutdown: deregister, then stop serving.  A CRASHED
        producer never runs this — its registry record simply goes stale
        and consumers evict it after ``alive_window_s``."""
        self._stop_hb.set()
        addr, rport = self._rdv
        if addr and rport:
            try:
                KVStoreClient(addr, int(rport)).delete(
                    REGISTRY_SCOPE, f"worker/{self.worker_id}")
            except Exception:
                pass
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None


def serve_dataset(dataset: Iterable[Any], worker_id: int = 0,
                  rendezvous_addr: Optional[str] = None,
                  rendezvous_port: Optional[int] = None) -> DataServiceWorker:
    """Start serving ``dataset`` (compute_worker_fn analog)."""
    w = DataServiceWorker(dataset, worker_id, rendezvous_addr,
                          rendezvous_port)
    w.start()
    return w


class RemoteDataset:
    """Trainer-side iterator (send_to_data_service read side): round-robins
    /next across live producers until every one is exhausted or evicted.

    With a rendezvous address, producers are discovered from the registry
    EVERY sweep (late joiners serve the tail of the epoch; stale-heartbeat
    producers are skipped).  A connection failure evicts the producer when
    its heartbeat is stale, OR after ``max_failures`` consecutive
    connection errors even with a fresh heartbeat (a wedged serving side
    under a live heartbeat thread must not stall every sweep forever);
    short transient failure streaks of a live producer are retried.  With
    a static ``endpoints`` list (no registry), eviction uses the
    ``max_failures`` streak alone."""

    def __init__(self, endpoints: Optional[List[str]] = None,
                 rendezvous_addr: Optional[str] = None,
                 rendezvous_port: Optional[int] = None,
                 num_workers: int = 1,
                 alive_window_s: float = ALIVE_WINDOW_S,
                 max_failures: int = 5):
        self._client = None
        self._alive_window = alive_window_s
        self._max_failures = max_failures
        self._static = endpoints
        # Heartbeat freshness is judged on the CONSUMER's clock by watching
        # the ts VALUE change (endpoint -> (last ts seen, local time it
        # changed)) — comparing a producer-host timestamp against this
        # host's clock would mark live producers dead under clock skew
        # larger than the window.
        self._hb_seen: Dict[str, tuple] = {}
        if endpoints is None:
            if not (rendezvous_addr and rendezvous_port):
                raise ValueError("pass endpoints or a rendezvous address")
            self._client = KVStoreClient(rendezvous_addr,
                                         int(rendezvous_port))
            # num_workers is kept for API compat; the registry is
            # authoritative.  ``endpoints`` is the discovery snapshot at
            # construction — iteration re-discovers every sweep.
            snap = self._registry()
            if not snap:
                raise ValueError("no data-service endpoints registered")
            self.endpoints = list(snap)
        elif not endpoints:
            raise ValueError("no data-service endpoints registered")
        else:
            self.endpoints = list(endpoints)

    def _registry(self) -> Optional[List[str]]:
        """Fresh-heartbeat producer endpoints from the registry, or None
        when the registry itself is UNREACHABLE — callers must treat None
        as "unknown" (keep the last view, evict nothing), never as "all
        producers gone": a KV blip mid-epoch must not silently end the
        epoch with batches undelivered."""
        try:
            records = sorted(self._client.scan(REGISTRY_SCOPE).items())
        except Exception as e:
            get_logger().warning(
                "data-service registry unreachable (treating producer "
                "liveness as unknown this sweep): %s", e)
            return None
        now = time.monotonic()
        out = []
        for key, raw in records:
            if not key.startswith("worker/"):
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            ep = rec.get("addr")
            seen = self._hb_seen.get(ep)
            if seen is None or seen[0] != rec.get("ts"):
                self._hb_seen[ep] = (rec.get("ts"), now)
                out.append(ep)
            elif now - seen[1] <= self._alive_window:
                out.append(ep)
        return out

    def __iter__(self) -> Iterator[Any]:
        import urllib.error
        import urllib.request
        exhausted: set = set()
        evicted: set = set()
        failures: Dict[str, int] = {}
        known = list(self.endpoints if self._client is None
                     else self._registry() or [])
        while True:
            if self._client is not None:
                reg = self._registry()
                if reg is not None:
                    known = reg
                # reg None = registry unreachable: keep the last-known
                # view (evict nothing, end nothing) and keep trying.
            live = [ep for ep in known
                    if ep not in exhausted and ep not in evicted]
            if not live:
                return
            progress = False
            for ep in live:
                try:
                    resp = urllib.request.urlopen(f"http://{ep}/next",
                                                  timeout=60)
                    # Any answered request proves the producer alive:
                    # reset its failure streak BEFORE the status check
                    # (a 204 drained-but-alive reply is a success, not a
                    # step toward 'consecutive failures').
                    failures.pop(ep, None)
                    # 204 = queue empty for the server's wait window:
                    # retry later.  urllib raises HTTPError only for
                    # status >= 400, so this must be an explicit status
                    # check, not an except branch.
                    if resp.status == 204:
                        continue
                    progress = True
                    yield pickle.loads(resp.read())
                except urllib.error.HTTPError as e:
                    if e.code == 410:  # producer exhausted: drop endpoint
                        failures.pop(ep, None)
                        exhausted.add(ep)
                    else:
                        raise
                except (urllib.error.URLError, ConnectionError,
                        TimeoutError, OSError) as e:
                    if self._evict(ep, failures, e):
                        evicted.add(ep)
            if not progress:
                time.sleep(0.2)

    def _evict(self, ep: str, failures: Dict[str, int],
               err: Exception) -> bool:
        """Decide whether a connection failure means DEAD (evict) or
        transient (retry).  Registry mode evicts on a STALE heartbeat
        (crashed producer) — and, like static mode, on ``max_failures``
        consecutive connection errors even while the heartbeat stays
        fresh: the heartbeat thread and the serving socket are
        independent, so a wedged HTTP server under a healthy heartbeat
        would otherwise be retried forever and stall every sweep.
        Static mode counts consecutive failures only."""
        if self._client is not None:
            reg = self._registry()
            if reg is None:
                # Registry unreachable: liveness is UNKNOWN — this is as
                # likely the consumer's own network blip as the producer's
                # fault, so neither eviction rule may fire and the failure
                # does NOT count toward the streak (a blip-inflated streak
                # would evict a healthy producer on its first real
                # transient error after recovery).
                return False
            failures[ep] = failures.get(ep, 0) + 1
            if ep not in reg:
                get_logger().warning(
                    "data-service producer %s unreachable with a stale "
                    "heartbeat; evicting (its undelivered batches are "
                    "lost, the epoch completes from the survivors): %s",
                    ep, err)
                return True
            if failures[ep] >= self._max_failures:
                get_logger().warning(
                    "data-service producer %s refused %d consecutive "
                    "connections despite a fresh heartbeat (serving side "
                    "wedged); evicting: %s", ep, failures[ep], err)
                return True
            # Heartbeat fresh and the failure streak still short: retry.
            return False
        failures[ep] = failures.get(ep, 0) + 1
        if failures[ep] >= self._max_failures:
            get_logger().warning(
                "data-service producer %s failed %d consecutive "
                "connections; evicting: %s", ep, failures[ep], err)
            return True
        return False
