"""Data service: host processes serve preprocessed batches to trainers.

Reference: the Horovod-managed ``tf.data.experimental.service`` cluster
(runner/common/service/compute_service.py:99 ComputeService — an RPC
registry of dispatchers and workers — plus tensorflow/data/
compute_service.py's send/read sides).  SURVEY.md §7 marks a TPU analog
optional; this is the minimal honest version: dedicated CPU-heavy hosts run
``serve_dataset`` (a batch producer + HTTP endpoint registered in the
rendezvous KV store), and each trainer iterates ``RemoteDataset`` which
round-robins pickled batches from the registered producers — decoupling
input preprocessing from accelerator hosts the way the reference's data
service does.
"""

from __future__ import annotations

import pickle
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Iterable, Iterator, List, Optional

from ..runner.http_server import KVStoreClient

REGISTRY_SCOPE = "dataservice"


class _BatchHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        if self.path != "/next":
            self.send_response(404)
            self.end_headers()
            return
        try:
            payload = self.server.batch_queue.get(timeout=30)
        except queue.Empty:
            # The None sentinel is consumed exactly once, so every reader
            # after the first must learn of exhaustion from the flag —
            # otherwise a second trainer retries 204s forever.
            code = 410 if self.server.exhausted else 204
            self.send_response(code)
            self.end_headers()
            return
        if payload is None:
            self.server.exhausted = True
            self.send_response(410)  # Gone: dataset exhausted
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class DataServiceWorker:
    """One producer endpoint (the reference's data-service *worker*): pulls
    batches from an iterable on a background thread, serves them over HTTP,
    registers itself in the rendezvous KV store."""

    def __init__(self, dataset: Iterable[Any], worker_id: int = 0,
                 rendezvous_addr: Optional[str] = None,
                 rendezvous_port: Optional[int] = None,
                 queue_size: int = 8):
        self.dataset = dataset
        self.worker_id = worker_id
        self._rdv = (rendezvous_addr, rendezvous_port)
        self._queue_size = queue_size
        self.httpd: Optional[ThreadingHTTPServer] = None

    def start(self) -> int:
        self.httpd = ThreadingHTTPServer(("0.0.0.0", 0), _BatchHandler)
        self.httpd.batch_queue = queue.Queue(maxsize=self._queue_size)
        self.httpd.exhausted = False
        port = self.httpd.server_address[1]

        def produce():
            # Sentinel goes out even if the dataset iterable raises, so
            # readers see 410 (exhausted) rather than polling 204 forever.
            try:
                for item in self.dataset:
                    self.httpd.batch_queue.put(pickle.dumps(item))
            finally:
                self.httpd.batch_queue.put(None)

        threading.Thread(target=produce, daemon=True,
                         name="hvd-data-producer").start()
        threading.Thread(target=self.httpd.serve_forever, daemon=True,
                         name="hvd-data-server").start()
        addr, rport = self._rdv
        if addr and rport:
            import socket
            my = socket.gethostbyname(socket.gethostname())
            KVStoreClient(addr, int(rport)).put(
                REGISTRY_SCOPE, f"worker/{self.worker_id}",
                f"{my}:{port}".encode())
        return port

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None


def serve_dataset(dataset: Iterable[Any], worker_id: int = 0,
                  rendezvous_addr: Optional[str] = None,
                  rendezvous_port: Optional[int] = None) -> DataServiceWorker:
    """Start serving ``dataset`` (compute_worker_fn analog)."""
    w = DataServiceWorker(dataset, worker_id, rendezvous_addr,
                          rendezvous_port)
    w.start()
    return w


class RemoteDataset:
    """Trainer-side iterator (send_to_data_service read side): round-robins
    /next across endpoints until every producer reports exhaustion."""

    def __init__(self, endpoints: Optional[List[str]] = None,
                 rendezvous_addr: Optional[str] = None,
                 rendezvous_port: Optional[int] = None,
                 num_workers: int = 1):
        if endpoints is None:
            if not (rendezvous_addr and rendezvous_port):
                raise ValueError("pass endpoints or a rendezvous address")
            client = KVStoreClient(rendezvous_addr, int(rendezvous_port))
            endpoints = []
            for w in range(num_workers):
                raw = client.get(REGISTRY_SCOPE, f"worker/{w}")
                if raw:
                    endpoints.append(raw.decode())
        if not endpoints:
            raise ValueError("no data-service endpoints registered")
        self.endpoints = endpoints

    def __iter__(self) -> Iterator[Any]:
        import urllib.error
        import urllib.request
        live = list(self.endpoints)
        while live:
            for ep in list(live):
                try:
                    resp = urllib.request.urlopen(f"http://{ep}/next",
                                                  timeout=60)
                    # 204 = producer drained-but-alive (queue empty for the
                    # server's wait window): retry later.  urllib raises
                    # HTTPError only for status >= 400, so this must be an
                    # explicit status check, not an except branch.
                    if resp.status == 204:
                        continue
                    yield pickle.loads(resp.read())
                except urllib.error.HTTPError as e:
                    if e.code == 410:  # producer exhausted: drop endpoint
                        live.remove(ep)
                    else:
                        raise
