from .data_loader_base import (  # noqa: F401
    BaseDataLoader, AsyncDataLoaderMixin, AsyncDataLoader,
    ShardedDataLoader)

from .service import (  # noqa: F401
    DataServiceWorker, RemoteDataset, serve_dataset)
