from .data_loader_base import (  # noqa: F401
    BaseDataLoader, AsyncDataLoaderMixin, AsyncDataLoader,
    ShardedDataLoader)
