"""Data loader base classes + sharded/prefetching loaders.

Reference: horovod/data/data_loader_base.py:20 (BaseDataLoader), :48
(AsyncDataLoaderMixin: a prefetch thread pushing batches into a bounded
queue so the accelerator never waits on host input).  The TPU build adds
``ShardedDataLoader``: rank-sharded iteration plus host→device prefetch of
the *next* batch while the current step runs — the JAX double-buffering
idiom that keeps HBM fed.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional


class BaseDataLoader:
    """Iteration interface (data_loader_base.py:20)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[Any]:
        return self._iterate()


class AsyncDataLoaderMixin:
    """Prefetch thread + bounded queue (data_loader_base.py:48).

    Mix in before a BaseDataLoader subclass::

        class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader): ...

    ``async_loader_queue_size=0`` disables prefetch (synchronous passthrough).
    """

    def __init__(self, *args, async_loader_queue_size: int = 2, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        super().__init__(*args, **kwargs)

    def __iter__(self) -> Iterator[Any]:
        if self.async_loader_queue_size <= 0:
            return super().__iter__()
        q: "queue.Queue" = queue.Queue(maxsize=self.async_loader_queue_size)
        sentinel = object()
        err: list = []

        def producer():
            try:
                for item in super(AsyncDataLoaderMixin, self)._iterate():
                    q.put(item)
            except BaseException as e:  # surface on the consumer side
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name="hvd-data-prefetch")
        t.start()

        def consume():
            while True:
                item = q.get()
                if item is sentinel:
                    if err:
                        raise err[0]
                    return
                yield item

        return consume()


class ShardedDataLoader(BaseDataLoader):
    """Rank-sharded loader: each rank sees every ``size``-th batch starting
    at its rank (the DistributedSampler contract), with optional device
    prefetch of the next batch (double buffering)."""

    def __init__(self, batches: Iterable[Any], rank: int = 0, size: int = 1,
                 device_prefetch: bool = False):
        self._batches = list(batches)
        self.rank = rank
        self.size = max(size, 1)
        self.device_prefetch = device_prefetch

    def __len__(self) -> int:
        n = len(self._batches)
        return (n - self.rank + self.size - 1) // self.size

    def _iterate(self):
        import jax
        shard = self._batches[self.rank::self.size]
        if not self.device_prefetch:
            yield from shard
            return
        prev = None
        for item in shard:
            nxt = jax.tree_util.tree_map(
                lambda x: jax.device_put(x), item)
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev


class AsyncDataLoader(AsyncDataLoaderMixin, ShardedDataLoader):
    """Convenience: sharded + background prefetch."""
