"""Storage abstraction for the Spark Estimator layer.

Reference: horovod/spark/common/store.py:38-540 — ``Store`` manages the
intermediate locations an Estimator run touches (train/val Parquet data,
checkpoints, logs) behind one path prefix, with LocalStore/HDFSStore/
S3Store/DBFSLocalStore variants.  Here one fsspec-backed implementation
covers every scheme fsspec knows (file://, hdfs://, s3://, gs://...) —
the reference's per-filesystem subclasses existed to wrap three different
client libraries; fsspec already unifies them.

No petastorm: data is plain Parquet written/read with pyarrow, sharded by
row group across ranks (spark/common/util.py prepare_data analog).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, List, Optional, Tuple


class Store:
    """Abstract run store (spark/common/store.py:38 Store).

    Layout under ``prefix_path``::

        <prefix>/intermediate_train_data/part-*.parquet
        <prefix>/intermediate_val_data/part-*.parquet
        <prefix>/runs/<run_id>/checkpoint.pkl
        <prefix>/runs/<run_id>/logs/
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")

    # -- construction -------------------------------------------------------

    @staticmethod
    def create(prefix_path: str, **kwargs) -> "Store":
        """Scheme-dispatching factory (store.py Store.create)."""
        return FilesystemStore(prefix_path, **kwargs)

    # -- path layout (get_*_path surface of store.py) -----------------------

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        sfx = f".{idx}" if idx is not None else ""
        return f"{self.prefix_path}/intermediate_train_data{sfx}"

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        sfx = f".{idx}" if idx is not None else ""
        return f"{self.prefix_path}/intermediate_val_data{sfx}"

    def get_test_data_path(self, idx: Optional[int] = None) -> str:
        sfx = f".{idx}" if idx is not None else ""
        return f"{self.prefix_path}/intermediate_test_data{sfx}"

    def get_run_path(self, run_id: str) -> str:
        return f"{self.prefix_path}/runs/{run_id}"

    def get_checkpoint_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/checkpoint.pkl"

    def get_logs_path(self, run_id: str) -> str:
        return f"{self.get_run_path(run_id)}/logs"

    def saving_runs(self) -> bool:
        """Whether checkpoints/logs persist (store.py saving_runs)."""
        return True

    # -- filesystem ops (subclass responsibility) ---------------------------

    def fs(self):
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    # -- pickled-object helpers (checkpoint.pkl) ---------------------------

    def write_obj(self, path: str, obj: Any) -> None:
        self.write_bytes(path, pickle.dumps(obj))

    def read_obj(self, path: str) -> Any:
        return pickle.loads(self.read_bytes(path))

    # -- parquet dataset helpers -------------------------------------------

    def is_parquet_dataset(self, path: str) -> bool:
        return bool(self.get_parquet_files(path))

    def get_parquet_files(self, path: str) -> List[str]:
        raise NotImplementedError


class FilesystemStore(Store):
    """fsspec-backed store: one class for local/HDFS/S3/GCS paths
    (collapses store.py LocalStore/HDFSStore/S3Store)."""

    def __init__(self, prefix_path: str, **fs_kwargs):
        super().__init__(prefix_path)
        import fsspec
        self._fs, self._root = fsspec.core.url_to_fs(self.prefix_path,
                                                     **fs_kwargs)

    def fs(self):
        return self._fs

    def _strip(self, path: str) -> str:
        # fsspec filesystems address paths without the scheme prefix.
        import fsspec
        return fsspec.core.url_to_fs(path)[1] if "://" in path else path

    def exists(self, path: str) -> bool:
        return self._fs.exists(self._strip(path))

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(self._strip(path), exist_ok=True)

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(self._strip(path), "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        p = self._strip(path)
        parent = p.rsplit("/", 1)[0] if "/" in p else ""
        if parent:
            self._fs.makedirs(parent, exist_ok=True)
        with self._fs.open(p, "wb") as f:
            f.write(data)

    def get_parquet_files(self, path: str) -> List[str]:
        p = self._strip(path)
        if not self._fs.exists(p):
            return []
        return sorted(f for f in self._fs.ls(p, detail=False)
                      if f.endswith(".parquet"))


class LocalStore(FilesystemStore):
    """Local-filesystem store (store.py LocalStore)."""

    def __init__(self, prefix_path: str):
        super().__init__(os.path.abspath(prefix_path))


def shard_row_groups(files: List[str], rank: int, size: int,
                     filesystem=None) -> List[Tuple[str, int]]:
    """Round-robin (file, row_group) assignment across ranks — the per-rank
    reader sharding petastorm's ``cur_shard``/``shard_count`` provided in
    the reference (torch/remote.py reader construction)."""
    import pyarrow.parquet as pq
    units: List[Tuple[str, int]] = []
    for f in files:
        src = filesystem.open(f, "rb") if filesystem is not None else f
        n = pq.ParquetFile(src).num_row_groups
        units.extend((f, g) for g in range(n))
    return [u for i, u in enumerate(units) if i % size == rank]
