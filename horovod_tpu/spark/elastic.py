"""Elastic training on Spark — ``horovod_tpu.spark.run_elastic``.

Reference: horovod/spark/runner.py:312 ``run_elastic`` (SparkDriverService +
SparkDriverHostDiscovery over registered Spark tasks, gloo elastic driver,
results gathered per final-world rank).

TPU-native shape: Spark tasks are *resource containers*, not ranks.  Each
task runs a small **task-pool loop** that registers itself (with heartbeats)
in the launcher's KV store and serves launch commands; the standard
``ElasticDriver`` (elastic/driver.py) treats the registered tasks as the
discoverable world — discovery is :class:`SparkTaskPoolDiscovery` reading
the same registry — and launches each assigned slot as a **subprocess
inside the owning task** (crash isolation: a worker ``os._exit`` kills the
incarnation, not the task container, which reports the failure and stays
available for the reshaped world — the reference gets the same split via
its per-task exec services).

The pickled function ships THROUGH the KV store (the reference ships it
through its driver service); no shared filesystem is assumed.  Worker
results land in the KV keyed (world_version, rank); the caller gets the
FINAL world's results ordered by rank, like ``ray_elastic``.

Everything Spark-specific is the thin ``_spark_task_pool`` adapter; the
task protocol itself is plain Python, so the elastic behavior (task death,
rejoin, reshape) is unit-testable without pyspark — mirroring how the
reference tests elastic-on-Spark through its fake task services.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import config as _config
from ..elastic.discovery import HostDiscovery
from ..elastic.driver import ElasticDriver
from ..elastic import coordinator_port_for
from ..runner import hosts as _hosts
from ..runner.http_server import KVStoreClient, RendezvousServer
from ..utils import get_logger

_SCOPE_TASKS = "se_tasks"      # task/{id} -> {host, ts}
_SCOPE_CTL = "se_ctl"          # shutdown marker
_SCOPE_FN = "se_fn"            # blob -> cloudpickled (fn, args, kwargs)
_SCOPE_LAUNCH = "se_launch"    # cmd/{task}/{seq} -> {env}
_SCOPE_DONE = "se_done"        # done/{task}/{seq} -> {code}
_SCOPE_RESULTS = "se_results"  # {world_version}/{rank} -> pickle(result)

_HEARTBEAT_S = 2.0
_ALIVE_WINDOW_S = 10.0
# How long the caller waits after driver.join() for the final world's
# result records to land (see run_elastic's ResultsRecorder note).
_RESULT_WAIT_S = 30.0

_BOOTSTRAP = r"""
import os, pickle, sys, urllib.request
base = "http://%s:%s" % (os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"],
                         os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"])
blob = urllib.request.urlopen(base + "/se_fn/blob", timeout=60).read()
fn, a, kw = pickle.loads(blob)
value = fn(*a, **(kw or {}))
# Report under the FINAL world seen by this incarnation: a survivor's
# rank/world changes across in-place resets (hvd.elastic refreshes env).
ver = os.environ.get("HVD_TPU_WORLD_VERSION", "0")
rank = os.environ.get("HOROVOD_RANK", "0")
req = urllib.request.Request("%s/se_results/%s/%s" % (base, ver, rank),
                             data=pickle.dumps(value), method="PUT")
urllib.request.urlopen(req, timeout=60).read()
"""


class SparkTaskPoolDiscovery(HostDiscovery):
    """Discovers hosts from the live task registry (the analog of
    SparkDriverHostDiscovery over SparkDriverService registrations,
    horovod/runner/elastic/discovery.py + spark/driver/host_discovery.py).
    A task is alive while its heartbeat is fresher than the window; an
    executor loss silently removes its tasks, shrinking the host's slot
    count, which the ElasticDriver's discovery loop turns into a reshape."""

    def __init__(self, kv_get_scope: Callable[[], Dict[str, bytes]],
                 alive_window_s: float = _ALIVE_WINDOW_S):
        self._scan = kv_get_scope
        self._window = alive_window_s

    def alive_tasks(self) -> Dict[int, str]:
        """task_id -> hostname for fresh heartbeats."""
        now = time.time()
        out = {}
        for key, raw in self._scan().items():
            if not key.startswith("task/"):
                continue
            rec = json.loads(raw)
            if now - rec["ts"] <= self._window:
                out[int(key[len("task/"):])] = rec["host"]
        return out

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        slots: Dict[str, int] = {}
        for _tid, host in self.alive_tasks().items():
            slots[host] = slots.get(host, 0) + 1
        return slots

    def task_for_slot(self, hostname: str, local_rank: int) -> Optional[int]:
        """The local_rank-th (by task id) alive task on ``hostname``."""
        ids = sorted(t for t, h in self.alive_tasks().items()
                     if h == hostname)
        return ids[local_rank] if local_rank < len(ids) else None


def task_pool_loop(addr: str, port: int, task_index: int,
                   hostname: Optional[str] = None,
                   python: Optional[List[str]] = None) -> None:
    """Runs inside one Spark task (or a test thread): heartbeat + serve
    launch commands as subprocesses until the driver signals shutdown."""
    client = KVStoreClient(addr, port)
    host = hostname or socket.gethostname()
    stop = threading.Event()

    def heartbeat():
        while not stop.is_set():
            try:
                client.put(_SCOPE_TASKS, f"task/{task_index}",
                           json.dumps({"host": host,
                                       "ts": time.time()}).encode())
            except Exception as e:
                # A missed heartbeat is recoverable (the driver allows
                # gaps) but never silent (HVD009): a run of them is this
                # task being evicted for a transport problem.
                get_logger().debug(
                    "task %d heartbeat put failed: %s", task_index, e)
            stop.wait(_HEARTBEAT_S)

    hb = threading.Thread(target=heartbeat, daemon=True,
                          name=f"se-heartbeat-{task_index}")
    hb.start()
    seq = 0

    def reconcile(seq: int) -> int:
        """A Spark-rescheduled incarnation restarts at seq=0 while the
        driver's counter kept going (completed launches' cmd records are
        deleted on consumption) — without this it would long-poll a seq
        that will never be written again.  The driver publishes
        ``next/{task}`` AFTER each cmd put, so: read next first, then scan
        for pending cmds.  A pending cmd >= seq is served; otherwise, if
        next says the counter is ahead AND no cmd for the gap survives
        (i.e. those launches were consumed), jump the counter forward."""
        try:
            nxt_raw = client.get(_SCOPE_LAUNCH, f"next/{task_index}")
            pending = sorted(
                int(k.rsplit("/", 1)[1])
                for k in client.scan(_SCOPE_LAUNCH)
                if k.startswith(f"cmd/{task_index}/"))
        except Exception:
            return seq
        ahead = [s for s in pending if s >= seq]
        if ahead:
            return ahead[0]
        if nxt_raw is not None:
            nxt = int(nxt_raw)
            if nxt > seq:
                return nxt
        return seq

    # After the first served cmd, seq provably tracks the driver's counter
    # (the loop increments it after every done), so steady-state reconcile
    # is a no-op; back off exponentially rather than scanning the scope on
    # every 1 s poll timeout — the rendezvous server's long-poll design
    # exists precisely to avoid that per-second load at scale.
    backoff, next_reconcile = 1.0, 0.0
    try:
        while True:
            if client.get(_SCOPE_CTL, "shutdown") is not None:
                return
            raw = client.get(_SCOPE_LAUNCH, f"cmd/{task_index}/{seq}",
                             wait=1.0)
            if raw is None:
                now = time.monotonic()
                if now >= next_reconcile:
                    new_seq = reconcile(seq)
                    backoff = 1.0 if new_seq != seq else min(backoff * 2,
                                                             30.0)
                    seq = new_seq
                    next_reconcile = now + backoff
                continue
            backoff, next_reconcile = 1.0, 0.0
            cmd = json.loads(raw)
            env = dict(os.environ)
            env.update(cmd["env"])
            proc = subprocess.Popen(
                (python or [sys.executable]) + ["-c", _BOOTSTRAP],
                env=env)
            while True:
                try:
                    code = proc.wait(timeout=0.5)
                    break
                except subprocess.TimeoutExpired:
                    if client.get(_SCOPE_CTL, "shutdown") is not None:
                        proc.kill()
                        proc.wait()
                        return
                    if client.get(_SCOPE_LAUNCH,
                                  f"abort/{task_index}/{seq}") is not None:
                        proc.terminate()
                        try:
                            code = proc.wait(timeout=5)
                        except subprocess.TimeoutExpired:
                            proc.kill()
                            code = proc.wait()
                        break
            client.put(_SCOPE_DONE, f"done/{task_index}/{seq}",
                       json.dumps({"code": code}).encode())
            if client.get(_SCOPE_LAUNCH, f"cmd/{task_index}/{seq}") is None:
                # The driver abandoned this launch (its abort wait timed
                # out and cleanup deleted cmd before our done landed):
                # nobody will ever consume the marker — drop it so aborts
                # can't leak KV keys for the run's lifetime.
                try:
                    client.delete(_SCOPE_DONE, f"done/{task_index}/{seq}")
                except Exception as e:
                    get_logger().debug(
                        "orphaned done-marker delete failed: %s", e)
            seq += 1
    finally:
        stop.set()
        hb.join(timeout=2 * _HEARTBEAT_S)


def _spark_task_pool(num_tasks: int, addr: str, port: int):
    """Launch ``num_tasks`` Spark tasks each running task_pool_loop; returns
    a join() callable.  Plain (non-barrier) scheduling: elastic semantics
    explicitly tolerate a partially-scheduled pool — whatever registers
    becomes the discoverable world (spark/runner.py:312 behavior)."""
    import pyspark
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a SparkSession "
                           "before run_elastic")

    def task_fn(it):
        for i in it:
            task_pool_loop(addr, port, i)
            yield i

    holder = {}

    def job():
        try:
            sc.parallelize(range(num_tasks), num_tasks) \
                .mapPartitions(task_fn).collect()
        except Exception as e:  # surfaced after driver.join
            holder["error"] = e

    th = threading.Thread(target=job, daemon=True, name="se-spark-job")
    th.start()

    def join(timeout=60.0):
        th.join(timeout)
        if "error" in holder:
            raise holder["error"]

    return join


def run_elastic(fn: Callable,
                args: tuple = (),
                kwargs: Optional[dict] = None,
                num_proc: Optional[int] = None,
                min_num_proc: Optional[int] = None,
                max_num_proc: Optional[int] = None,
                start_timeout: Optional[float] = None,
                elastic_timeout: Optional[float] = None,
                reset_limit: Optional[int] = None,
                cooldown_range: Optional[tuple] = (5.0, 60.0),
                env: Optional[Dict[str, str]] = None,
                verbose: int = 1,
                _task_pool_factory: Optional[Callable] = None) -> List[Any]:
    """Run ``fn`` elastically over Spark tasks; returns the FINAL world's
    per-rank results ordered by rank (horovod/spark/runner.py:312).

    ``fn`` should wrap its training loop in ``hvd.elastic.run`` to survive
    reshapes.  ``cooldown_range`` bounds the failed-host blacklist
    cooldown (reference --blacklist-cooldown-range); unlike the ssh
    launcher it DEFAULTS ON here, because Spark re-registers tasks from
    the same executor hosts — a permanent blacklist would starve the
    reshape whenever the pool has few hosts.  Pass ``None`` for the
    reference's permanent-blacklist behavior.
    ``_task_pool_factory(num_tasks, addr, port) -> join_fn`` is injectable
    for tests (threads instead of Spark tasks)."""
    import cloudpickle

    kwargs = kwargs or {}
    start_timeout = start_timeout or float(
        os.environ.get("HOROVOD_SPARK_START_TIMEOUT", "600"))
    elastic_timeout = elastic_timeout or float(
        os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    if num_proc is None:
        if _task_pool_factory is None:
            import pyspark
            sc = pyspark.SparkContext._active_spark_context
            if sc is None:
                raise RuntimeError("no active SparkContext")
            num_proc = sc.defaultParallelism
        else:
            raise ValueError("num_proc is required with a custom task pool")
    min_np = min_num_proc or num_proc
    max_np = max_num_proc or num_proc

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = "127.0.0.1" if _task_pool_factory else \
        socket.gethostbyname(socket.gethostname())
    client = KVStoreClient(addr, port)
    client.put(_SCOPE_FN, "blob",
               cloudpickle.dumps((fn, args, kwargs)))

    def scan_tasks():
        return client.scan(_SCOPE_TASKS)

    discovery = SparkTaskPoolDiscovery(scan_tasks)
    driver = ElasticDriver(rendezvous, discovery, min_np, max_np,
                           reset_limit=reset_limit,
                           cooldown_range=cooldown_range,
                           timeout=elastic_timeout)
    pool_join = (_task_pool_factory or _spark_task_pool)(
        max_np, addr, port)

    launch_seq: Dict[int, int] = {}     # task_id -> next launch seq
    seq_lock = threading.Lock()
    task_locks: Dict[int, threading.Lock] = {}  # per-task launch ordering
    extra_env = dict(env or {})
    gc_state = {"version": -1}

    def _gc_stale_results(world_version: int) -> None:
        """Results of superseded worlds are never read (only the FINAL
        world's are returned); drop them on each reshape so a long
        elastic run doesn't grow the launcher's KV store without bound."""
        with seq_lock:
            if world_version <= gc_state["version"]:
                return
            gc_state["version"] = world_version
        try:
            for k in client.scan(_SCOPE_RESULTS):
                if int(k.split("/")[0]) < world_version:
                    client.delete(_SCOPE_RESULTS, k)
        except Exception as e:
            get_logger().debug(
                "stale-results GC failed (retried next reshape): %s", e)

    def worker_fn(slot: _hosts.SlotInfo, terminate_event: threading.Event,
                  world_version: int) -> int:
        # Never raise: the ElasticDriver's worker thread has no except
        # path, and an escaped KV transport error would leave the Worker
        # registered forever — driver.join() would hang instead of the
        # failure being recorded and reshaped around.
        try:
            while True:
                code = _worker_fn_inner(slot, terminate_event,
                                        world_version)
                if code != 0 or terminate_event.is_set():
                    return code
                # The launch completed cleanly, but this Worker thread may
                # have been ADOPTED into a newer world meanwhile (the
                # driver keeps live workers across reshapes).  Launches
                # are WORLD-scoped in the task-pool protocol — serve the
                # current world with a fresh launch when this slot is
                # still assigned.  retire_if_settled decides atomically
                # with the driver's adoption (same lock): either we serve
                # the newer world, or the record is marked retired so a
                # reshape racing our exit replaces it with a fresh launch
                # instead of keeping an exiting thread.
                settled, new_slot, cur = driver.retire_if_settled(
                    slot.hostname, slot.local_rank, world_version,
                    terminate_event=terminate_event)
                if settled:
                    return 0
                slot, world_version = new_slot, cur
        except Exception:
            get_logger().warning(
                "spark elastic: worker slot %s:%d failed in the launch "
                "protocol", slot.hostname, slot.local_rank, exc_info=True)
            return 1

    def _worker_fn_inner(slot, terminate_event, world_version) -> int:
        from ..elastic.launch_support import slot_env
        _gc_stale_results(world_version)
        task_id = discovery.task_for_slot(slot.hostname, slot.local_rank)
        if task_id is None:
            return 1  # task vanished between discovery and launch
        wenv = {
            **slot_env(slot, world_version, addr, port, driver,
                       coord_base=port + 1),
            **extra_env,
        }
        with seq_lock:
            tlock = task_locks.setdefault(task_id, threading.Lock())
        seq = None
        try:
            # Alloc + both puts under a PER-TASK lock: cmd must precede
            # next and next must be monotonic *per task* (a rescheduled
            # incarnation's reconcile() reads next first, then scans
            # pending cmds — seeing next==seq+1 with no cmd/{seq} pending
            # proves launch seq was already consumed and skipping it is
            # safe).  Cross-task launches stay parallel; a hung KV request
            # stalls only this task's launch, not the whole reshape.  The
            # puts sit inside the try so a put failure after cmd landed
            # still reaches the finally's cleanup — otherwise the task
            # loop would serve a launch no worker thread tracks.
            with tlock:
                with seq_lock:
                    seq = launch_seq.get(task_id, 0)
                    launch_seq[task_id] = seq + 1
                client.put(_SCOPE_LAUNCH, f"cmd/{task_id}/{seq}",
                           json.dumps({"env": wenv}).encode())
                client.put(_SCOPE_LAUNCH, f"next/{task_id}",
                           str(seq + 1).encode())
            while True:
                raw = client.get(_SCOPE_DONE, f"done/{task_id}/{seq}",
                                 wait=1.0)
                if raw is not None:
                    return int(json.loads(raw)["code"])
                if terminate_event.is_set():
                    client.put(_SCOPE_LAUNCH, f"abort/{task_id}/{seq}",
                               b"1")
                    raw = client.get(_SCOPE_DONE, f"done/{task_id}/{seq}",
                                     wait=10.0)
                    return int(json.loads(raw)["code"]) if raw else 143
                if discovery.task_for_slot(slot.hostname,
                                           slot.local_rank) != task_id:
                    get_logger().warning(
                        "spark elastic: task %d (slot %s:%d) lost mid-run",
                        task_id, slot.hostname, slot.local_rank)
                    return 1
        finally:
            # Consume the records: a Spark-rescheduled incarnation of this
            # task must not replay completed/aborted launches (its
            # reconcile() skips forward using the next/{task} pointer once
            # the cmd is gone — see task_pool_loop).  done/ is consumed
            # too so a long-elastic run's KV store stays bounded; a done
            # marker that lands AFTER this cleanup (slow-dying abortee) is
            # dropped by the task loop's own cmd-gone check.
            if seq is not None:
                for scope, k in ((_SCOPE_LAUNCH, f"cmd/{task_id}/{seq}"),
                                 (_SCOPE_LAUNCH, f"abort/{task_id}/{seq}"),
                                 (_SCOPE_DONE, f"done/{task_id}/{seq}")):
                    try:
                        client.delete(scope, k)
                    except Exception as e:
                        get_logger().debug(
                            "launch-marker cleanup delete failed: %s", e)

    t0 = time.time()
    while not discovery.find_available_hosts_and_slots():
        if time.time() - t0 > start_timeout:
            rendezvous.stop()
            raise TimeoutError(
                f"no Spark task registered within {start_timeout}s "
                "(HOROVOD_SPARK_START_TIMEOUT); check cluster resources")
        time.sleep(0.2)

    try:
        driver.start(worker_fn)
        driver.join()
        if driver.error_message:
            raise RuntimeError(driver.error_message)
        final = driver.world_version
        expected = {s.rank for s in driver.current_assignments()}
        # ResultsRecorder semantics (runner/elastic/driver.py:113
        # get_results): conclude only after every final-world rank's
        # result is RECORDED, not merely after every worker exited.  A
        # rejoined incarnation's result PUT travels a different socket
        # than its done marker, so under host load the publication can
        # trail the driver's finished-check by a scheduling quantum —
        # poll briefly instead of failing on the first scan (the r4
        # in-suite flake).  The wait is bounded: a rank that truly never
        # published (crashed mid-PUT) still surfaces the forensic error.
        deadline = time.monotonic() + _RESULT_WAIT_S
        while True:
            raw_results = client.scan(_SCOPE_RESULTS)
            results = {int(k.split("/")[1]): pickle.loads(v)
                       for k, v in raw_results.items()
                       if k.startswith(f"{final}/")}
            missing = sorted(expected - set(results))
            if not missing:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"spark elastic finished but ranks {missing} reported "
                    f"no result for final world {final} within "
                    f"{_RESULT_WAIT_S:.0f}s "
                    f"(result keys present: {sorted(raw_results)})")
            time.sleep(0.1)
        return [results[r] for r in sorted(expected)]
    finally:
        client.put(_SCOPE_CTL, "shutdown", b"1")
        try:
            pool_join()
        except Exception:
            get_logger().warning("spark elastic task pool join failed",
                                 exc_info=True)
        driver.stop()
        rendezvous.stop()
