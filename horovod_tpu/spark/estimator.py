"""Spark ML Estimator layer: ``HorovodTpuEstimator.fit(df)`` → trained
``TpuTransformer``.

Reference: horovod/spark/common/estimator.py:25 (HorovodEstimator: fit
materializes the DataFrame to Parquet via a Store, trains inside
horovod.spark.run, returns a Spark ML Transformer holding the model) and
keras/estimator.py:98 (parameter surface).  The petastorm reader stack is
replaced by plain pyarrow Parquet readers sharded by row group
(store.shard_row_groups) — petastorm existed to stream Parquet into
framework tensors; pyarrow → numpy → jax does that directly.

Works with or without pyspark:

* a **pyspark DataFrame** is written with ``df.write.parquet`` and training
  launches on Spark barrier tasks (spark_integration.run);
* a **pandas DataFrame** (or anything ``pandas.DataFrame(data)`` accepts)
  is written with pyarrow and training launches through the local
  multi-process launcher (``horovod_tpu.run``) — the same per-rank training
  function either way.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, List, Optional, Sequence, Union

from .store import Store, shard_row_groups


def _is_spark_df(df) -> bool:
    mod = type(df).__module__ or ""
    return mod.startswith("pyspark.")


def _resolve_loss(loss) -> Callable:
    """Accept a callable(pred, label)->scalar or a named loss
    (keras/estimator.py accepts keras loss names)."""
    if callable(loss):
        return loss
    import jax.numpy as jnp
    import optax
    name = str(loss).lower()
    if name in ("mse", "mean_squared_error"):
        return lambda p, y: jnp.mean((p - y) ** 2)
    if name in ("mae", "mean_absolute_error"):
        return lambda p, y: jnp.mean(jnp.abs(p - y))
    if name in ("sparse_categorical_crossentropy", "softmax_cross_entropy",
                "cross_entropy"):
        return lambda p, y: optax.softmax_cross_entropy_with_integer_labels(
            p, y).mean()
    raise ValueError(f"unknown loss {loss!r}; pass a callable(pred, label)")


def _columns_to_array(table_cols: dict, cols: Sequence[str]):
    """Assemble named columns into one [n, ...] numpy array: scalar columns
    stack to [n, len(cols)]; a single list-valued column keeps its row
    shape [n, k] (the reference's DenseVector feature column analog)."""
    import numpy as np
    arrs = []
    for c in cols:
        v = table_cols[c]
        first = v[0]
        if isinstance(first, (list, tuple, np.ndarray)):
            arrs.append(np.stack([np.asarray(x) for x in v]))
        else:
            arrs.append(np.asarray(v))
    if len(arrs) == 1:
        return arrs[0]
    return np.stack(arrs, axis=-1)


class RowGroupStream:
    """Streams a rank's (file, row_group) units one group at a time —
    the petastorm-reader contract the reference's estimator relies on
    (spark/common/estimator.py:25: bigger-than-memory shards stream from
    Parquet): peak memory is one row group plus a partial batch, never
    the whole shard.  Epoch shuffling is two-level, the standard
    streaming scheme: the row-group ORDER is re-permuted every epoch and
    rows shuffle within each group; successive epochs see different
    batch compositions without ever materializing the shard.

    ``peak_rows_resident`` records the largest row count ever held, so
    tests can assert the bounded-memory contract on shards much larger
    than the budget."""

    # Open-file cache bound: a shard spanning hundreds of Parquet files
    # must not hold one fd per file for the fit's lifetime (the bounded-
    # resource claim covers descriptors too); a few stay open because the
    # per-epoch group shuffle revisits files in mixed order.
    MAX_OPEN_FILES = 4

    def __init__(self, units, feature_cols, label_cols, filesystem=None,
                 seed: int = 0):
        self.units = list(units)
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.filesystem = filesystem
        self.seed = seed
        self._files: dict = {}  # insertion-ordered: LRU eviction
        self.peak_rows_resident = 0

    def _pf(self, f):
        if f in self._files:
            entry = self._files.pop(f)  # re-insert: most-recently-used
            self._files[f] = entry
            return entry[0]
        while len(self._files) >= self.MAX_OPEN_FILES:
            self._close_one(next(iter(self._files)))
        import pyarrow.parquet as pq
        src = self.filesystem.open(f, "rb") \
            if self.filesystem is not None else f
        pf = pq.ParquetFile(src)
        self._files[f] = (pf, src if src is not f else None)
        return pf

    def _close_one(self, f) -> None:
        pf, src = self._files.pop(f)
        for h in (pf, src):
            if h is None:
                continue
            try:
                h.close()
            except Exception:
                pass

    def close(self) -> None:
        """Release every open Parquet handle (idempotent)."""
        for f in list(self._files):
            self._close_one(f)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def num_rows(self) -> int:
        """Total rows across the shard, from metadata only (no data read)."""
        return sum(self._pf(f).metadata.row_group(g).num_rows
                   for f, g in self.units)

    def _read_group(self, f, g):
        import numpy as np
        d = self._pf(f).read_row_group(g).to_pydict()
        X = _columns_to_array(d, self.feature_cols)
        Y = _columns_to_array(d, self.label_cols)
        return np.asarray(X), np.asarray(Y)

    def iter_groups(self):
        """(X, Y) per row group — validation evaluates group-wise."""
        for f, g in self.units:
            yield self._read_group(f, g)

    def iter_batches(self, batch: int, epoch: int = 0,
                     shuffle: bool = True):
        """Exactly-``batch``-row arrays (static shapes for jit), streamed.
        Yields floor(num_rows / batch) batches, or one wrap-filled batch
        when the shard is smaller than a batch.  The sub-batch tail of
        each group carries into the next group's batches."""
        import numpy as np
        rng = np.random.RandomState(self.seed * 100003 + epoch)
        order = list(self.units)
        if shuffle:
            rng.shuffle(order)
        carryX = carryY = None
        yielded = 0
        for f, g in order:
            X, Y = self._read_group(f, g)
            if shuffle:
                p = rng.permutation(len(X))
                X, Y = X[p], Y[p]
            if carryX is not None and len(carryX):
                X = np.concatenate([carryX, X])
                Y = np.concatenate([carryY, Y])
            self.peak_rows_resident = max(self.peak_rows_resident, len(X))
            i = 0
            while i + batch <= len(X):
                yield X[i:i + batch], Y[i:i + batch]
                yielded += 1
                i += batch
            carryX, carryY = X[i:], Y[i:]
        if yielded == 0 and carryX is not None and len(carryX):
            # Shard smaller than one batch: wrap-fill (static shapes).
            reps = -(-batch // len(carryX))
            yield (np.concatenate([carryX] * reps)[:batch],
                   np.concatenate([carryY] * reps)[:batch])


def _estimator_train_fn(cfg: dict) -> List[dict]:
    """Per-rank training body (reference: torch/remote.py:107 RemoteTrainer
    — runs inside every Spark task / launcher worker)."""
    if cfg.get("platform"):
        import jax
        jax.config.update("jax_platforms", cfg["platform"])
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rank, size = hvd.rank(), hvd.size()
    store: Store = cfg["store"]

    import contextlib
    with contextlib.ExitStack() as streams:
        fs = store.fs()
        units = shard_row_groups(store.get_parquet_files(cfg["train_path"]),
                                 rank, size, filesystem=fs)
        stream = streams.enter_context(
            RowGroupStream(units, cfg["feature_cols"], cfg["label_cols"],
                           filesystem=fs, seed=cfg["seed"] + rank))
        total_rows = stream.num_rows()
        if total_rows == 0:
            raise ValueError(
                f"rank {rank} received no parquet row groups; write the "
                f"training data with at least {size} row groups "
                f"(row_group_size small enough) or lower num_proc")
        vstream = None
        if cfg.get("val_path"):
            vunits = shard_row_groups(
                store.get_parquet_files(cfg["val_path"]), rank, size,
                filesystem=fs)
            vstream = streams.enter_context(
                RowGroupStream(vunits, cfg["feature_cols"],
                               cfg["label_cols"], filesystem=fs))
        return _estimator_train_loop(cfg, stream, vstream, total_rows)


def _estimator_train_loop(cfg, stream, vstream, total_rows) -> List[dict]:
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd

    rank = hvd.rank()
    store: Store = cfg["store"]
    model, loss_fn = cfg["model"], _resolve_loss(cfg["loss"])
    batch = cfg["batch_size"]
    X0, _ = next(stream.iter_batches(min(batch, total_rows), epoch=0,
                                     shuffle=False))
    params = model.init(jax.random.PRNGKey(cfg["seed"]),
                        jnp.asarray(X0[:1]))
    # Rank 0's initialization reaches everyone (BroadcastGlobalVariables
    # idiom) — model.init is deterministic here, but user models may not be.
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt = hvd.DistributedOptimizer(cfg["optimizer"])
    opt_state = opt.init(params)

    @jax.jit
    def grad_step(p, xb, yb):
        return jax.value_and_grad(
            lambda q: loss_fn(model.apply(q, xb), yb))(p)

    @jax.jit
    def eval_loss(p, xb, yb):
        return loss_fn(model.apply(p, xb), yb)

    # Equal step counts across ranks: collectives are SPMD-total, so every
    # rank must dispatch the same number of optimizer updates per epoch
    # (the reference equalizes via steps_per_epoch / join; MIN-allreduce of
    # the local batch count is the static-shape-friendly form).
    local_steps = max(total_rows // batch, 1)
    nsteps = int(hvd.allreduce(jnp.asarray(float(local_steps)),
                               op=hvd.Min, name="est.steps"))
    from ..callbacks import CallbackList
    cbs = CallbackList(cfg.get("callbacks") or [])
    cbs.on_train_begin()
    history: List[dict] = []
    for epoch in range(cfg["epochs"]):
        cbs.on_epoch_begin(epoch)
        # Streamed batches, two-level shuffle per epoch (RowGroupStream):
        # the shard never materializes — bigger-than-memory shards train
        # at one-row-group peak memory (the petastorm contract).
        batches = stream.iter_batches(batch, epoch=epoch,
                                      shuffle=cfg["shuffle"])
        ep_loss = 0.0
        for _ in range(nsteps):
            xb, yb = next(batches)
            loss, grads = grad_step(params, jnp.asarray(xb),
                                    jnp.asarray(yb))
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            ep_loss += float(loss)
        entry = {"epoch": epoch, "loss": float(hvd.allreduce(
            jnp.asarray(ep_loss / nsteps), op=hvd.Average,
            name="est.loss"))}
        if cfg.get("val_path"):
            # EVERY rank dispatches this collective even if its shard got no
            # validation row groups (collectives are SPMD-total; a guarded
            # dispatch would deadlock).  Weighted sum handles the raggedness.
            # Validation streams group-wise too: the row-weighted sum over
            # groups equals the full-shard loss for mean-reducing losses.
            vloss_sum, vrows = 0.0, 0.0
            if vstream is not None:
                for vxb, vyb in vstream.iter_groups():
                    if len(vxb) == 0:
                        continue
                    vloss_sum += float(eval_loss(
                        params, jnp.asarray(vxb),
                        jnp.asarray(vyb))) * len(vxb)
                    vrows += len(vxb)
            agg = hvd.allreduce(jnp.asarray([vloss_sum, vrows]), op=hvd.Sum,
                                name="est.val_loss")
            if float(agg[1]) > 0:
                entry["val_loss"] = float(agg[0]) / float(agg[1])
        history.append(entry)
        if cfg["verbose"] and rank == 0:
            print(f"[estimator] epoch {epoch + 1}/{cfg['epochs']}: {entry}")
        # Fit callbacks (the reference estimators accept Keras callbacks).
        # The metrics in ``entry`` are allreduce-averaged, so callback
        # decisions (e.g. EarlyStoppingCallback) are rank-consistent and
        # every rank breaks out of the epoch loop together — an
        # inconsistent break would strand peers in the next epoch's
        # collectives.
        cbs.on_epoch_end(epoch, logs=entry)
        if cbs.stop_training:
            if cfg["verbose"] and rank == 0:
                print(f"[estimator] early stop after epoch {epoch + 1}")
            break
    if rank == 0:
        store.write_obj(store.get_checkpoint_path(cfg["run_id"]), {
            "params": jax.device_get(params),
            "history": history,
            "feature_cols": cfg["feature_cols"],
            "label_cols": cfg["label_cols"],
        })
    return history


class HorovodTpuEstimator:
    """Estimator with the reference's fit contract
    (spark/common/estimator.py:25; parameter names follow
    keras/estimator.py:98).

    Args:
      model: a flax ``linen.Module`` (anything with ``.init(rng, x)`` /
        ``.apply(params, x)``).
      optimizer: an optax gradient transformation.
      loss: callable(pred, label) -> scalar, or one of "mse", "mae",
        "sparse_categorical_crossentropy".
      feature_cols / label_cols: DataFrame column names.
      store: a ``Store`` (defaults to a LocalStore under /tmp).
      validation: fraction in (0, 1) for a random split, or the name of a
        boolean column selecting validation rows (estimator.py semantics).
      num_proc: ranks to train with (Spark tasks or local processes).
      callbacks: fit callbacks (horovod_tpu.callbacks.Callback objects,
        cloudpickled to the workers): ``on_epoch_end(epoch, logs)`` fires
        with the rank-averaged metrics entry, and a callback setting
        ``stop_training`` (e.g. EarlyStoppingCallback) ends the fit on
        every rank together — the Keras-callback surface the reference's
        estimators accept.
      worker_platform: force a jax platform inside workers (tests use
        "cpu"; leave None on real TPU hosts).
    """

    def __init__(self,
                 model=None,
                 optimizer=None,
                 loss=None,
                 feature_cols: Optional[Sequence[str]] = None,
                 label_cols: Optional[Sequence[str]] = None,
                 batch_size: int = 32,
                 epochs: int = 1,
                 validation: Union[None, float, str] = None,
                 store: Optional[Store] = None,
                 num_proc: int = 1,
                 shuffle: bool = True,
                 verbose: int = 1,
                 run_id: Optional[str] = None,
                 random_seed: int = 0,
                 callbacks: Optional[list] = None,
                 worker_platform: Optional[str] = None):
        if model is None or optimizer is None or loss is None:
            raise ValueError("model, optimizer and loss are required")
        if not feature_cols or not label_cols:
            raise ValueError("feature_cols and label_cols are required")
        _resolve_loss(loss)  # validate early
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.validation = validation
        self.store = store
        self.num_proc = num_proc
        self.shuffle = shuffle
        self.verbose = verbose
        self.run_id = run_id
        self.random_seed = random_seed
        self.callbacks = list(callbacks or [])
        self.worker_platform = worker_platform
        self.history: List[dict] = []

    # -- data materialization (spark/common/util.py prepare_data analog) ----

    def _write_parquet(self, df, store: Store):
        """Materialize ``df`` under the store's intermediate paths; returns
        (train_path, val_path or None)."""
        train_path = store.get_train_data_path()
        val_path = store.get_val_data_path()
        if _is_spark_df(df):
            train_df, val_df = self._split_spark(df)
            train_df.write.mode("overwrite").parquet(train_path)
            if val_df is not None:
                val_df.write.mode("overwrite").parquet(val_path)
            return train_path, (val_path if val_df is not None else None)
        return self._write_pandas(df, store, train_path, val_path)

    def _split_spark(self, df):
        if self.validation is None:
            return df, None
        if isinstance(self.validation, str):
            return (df.filter(f"NOT {self.validation}"),
                    df.filter(self.validation))
        frac = float(self.validation)
        train_df, val_df = df.randomSplit([1.0 - frac, frac],
                                          seed=self.random_seed)
        return train_df, val_df

    def _write_pandas(self, df, store: Store, train_path: str,
                      val_path: str):
        import numpy as np
        import pandas as pd
        import pyarrow as pa
        import pyarrow.parquet as pq
        if not isinstance(df, pd.DataFrame):
            df = pd.DataFrame(df)
        if self.validation is None:
            train_df, val_df = df, None
        elif isinstance(self.validation, str):
            mask = df[self.validation].astype(bool)
            train_df = df[~mask].drop(columns=[self.validation])
            val_df = df[mask].drop(columns=[self.validation])
        else:
            rng = np.random.RandomState(self.random_seed)
            mask = rng.rand(len(df)) < float(self.validation)
            train_df, val_df = df[~mask], df[mask]

        def write(frame, path):
            # Enough row groups that every rank gets data
            # (store.shard_row_groups shards by row group).
            rows_per_group = max(1, len(frame) // max(self.num_proc * 4, 1))
            fs = store.fs()
            p = store._strip(path)
            fs.makedirs(p, exist_ok=True)
            pq.write_table(pa.Table.from_pandas(frame.reset_index(drop=True)),
                           f"{p}/part-00000.parquet",
                           row_group_size=rows_per_group,
                           filesystem=fs)

        write(train_df, train_path)
        if val_df is not None and len(val_df):
            write(val_df, val_path)
            return train_path, val_path
        return train_path, None

    # -- fit (estimator.py:25 fit -> Transformer) ---------------------------

    def fit(self, df) -> "TpuTransformer":
        from .store import LocalStore
        store = self.store
        if store is None:
            import tempfile
            store = LocalStore(tempfile.mkdtemp(prefix="hvd_tpu_store_"))
        run_id = self.run_id or \
            f"run_{time.strftime('%Y%m%d_%H%M%S')}_{uuid.uuid4().hex[:6]}"
        train_path, val_path = self._write_parquet(df, store)
        cfg = {
            "model": self.model, "optimizer": self.optimizer,
            "loss": self.loss, "feature_cols": self.feature_cols,
            "label_cols": self.label_cols, "batch_size": self.batch_size,
            "epochs": self.epochs, "shuffle": self.shuffle,
            "verbose": self.verbose, "seed": self.random_seed,
            "callbacks": self.callbacks,
            "store": store, "run_id": run_id,
            "train_path": train_path, "val_path": val_path,
            "platform": self.worker_platform,
        }
        try:
            import pyspark
            from pyspark import SparkContext
            has_spark_ctx = SparkContext._active_spark_context is not None
        except ImportError:
            has_spark_ctx = False
        if has_spark_ctx and _is_spark_df(df):
            from .. import spark_integration
            results = spark_integration.run(
                _estimator_train_fn, args=(cfg,), num_proc=self.num_proc)
        else:
            from .. import runner
            results = runner.run(_estimator_train_fn, args=(cfg,),
                                 np=self.num_proc)
        self.history = results[0]
        ckpt = store.read_obj(store.get_checkpoint_path(run_id))
        return TpuTransformer(model=self.model, params=ckpt["params"],
                              feature_cols=self.feature_cols,
                              label_cols=self.label_cols,
                              history=ckpt["history"], run_id=run_id,
                              store=store)


def _append_predictions(model, params, feature_cols, outs, pdf):
    """Predict one pandas frame and append ``<label>__output`` columns —
    the single definition shared by distributed (mapInPandas) and
    in-process transform so the two paths cannot diverge."""
    import numpy as np
    import jax.numpy as jnp
    pdf = pdf.copy()
    if len(pdf) == 0:
        # Empty partitions are routine after filters/repartitions; emit
        # the frame with empty output columns, matching schema.
        for c in outs:
            pdf[c] = []
        return pdf
    cols = {c: list(pdf[c]) for c in feature_cols}
    X = _columns_to_array(cols, feature_cols)
    pred = np.asarray(model.apply(params, jnp.asarray(X)))
    if len(outs) == 1:
        pdf[outs[0]] = list(pred) if pred.ndim > 1 else pred
    else:
        for i, c in enumerate(outs):
            pdf[c] = pred[..., i]
    return pdf


def _transform_partition(payload: bytes, frames):
    """Executor-side batch predictor for ``TpuTransformer.transform`` on a
    pyspark DataFrame (the mapInPandas UDF body, factored out so the logic
    is unit-testable without a Spark cluster).  ``payload`` is a
    cloudpickled {model, params (host copies), feature_cols, label_cols};
    yields each incoming pandas frame with ``<label>__output`` columns
    appended.  Reference: HorovodModel.transform's pandas-UDF per-partition
    prediction (spark/torch/estimator.py, keras/estimator.py)."""
    import os
    # Executors have no accelerator claim; force the CPU backend before
    # jax initializes (a worker trying to grab the TPU relay would fail).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import cloudpickle
    d = cloudpickle.loads(payload)
    outs = [f"{c}__output" for c in d["label_cols"]]
    for pdf in frames:
        yield _append_predictions(d["model"], d["params"],
                                  d["feature_cols"], outs, pdf)


class TpuTransformer:
    """Trained-model Transformer (spark/common/estimator.py
    HorovodModel.transform analog): adds ``<label>__output`` prediction
    columns.  Accepts a pandas or pyspark DataFrame; pyspark input is
    predicted DISTRIBUTED on the executors via ``mapInPandas`` (the
    reference's pandas-UDF pattern), pandas input on the caller."""

    def __init__(self, model, params, feature_cols, label_cols,
                 history=None, run_id=None, store=None):
        self.model = model
        self.params = params
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.history = history or []
        self.run_id = run_id
        self.store = store

    def output_cols(self) -> List[str]:
        return [f"{c}__output" for c in self.label_cols]

    def predict(self, X):
        import jax.numpy as jnp
        return self.model.apply(self.params, jnp.asarray(X))

    def _udf_payload(self) -> bytes:
        import cloudpickle
        import jax
        return cloudpickle.dumps({
            "model": self.model, "params": jax.device_get(self.params),
            "feature_cols": self.feature_cols,
            "label_cols": self.label_cols})

    def transform(self, df):
        import numpy as np
        if _is_spark_df(df):
            # DISTRIBUTED inference: each executor partition predicts via
            # _transform_partition (mapInPandas), never funneling rows
            # through the driver.  The output schema extends the input with
            # one column per label; its Spark type is inferred from a
            # one-row driver-side prediction (array column for vector
            # outputs, double for scalars).
            from pyspark.sql.types import (
                ArrayType, DoubleType, StructField, StructType)
            sample = df.limit(1).toPandas()
            if len(sample) == 0:
                # Empty DataFrame: no row to infer the vector-vs-scalar
                # output shape from; default to scalar columns.  Caveat: a
                # vector-output model's empty transform then has DoubleType
                # where a non-empty one has ArrayType — unioning the two
                # needs an explicit cast (unknowable here without a row).
                out_type = DoubleType()
            else:
                scols = {c: list(sample[c]) for c in self.feature_cols}
                spred = np.asarray(self.predict(
                    _columns_to_array(scols, self.feature_cols)))
                out_type = ArrayType(DoubleType()) if spred.ndim > 1 \
                    and len(self.output_cols()) == 1 else DoubleType()
            schema = StructType(list(df.schema.fields) + [
                StructField(c, out_type, True) for c in self.output_cols()])
            payload = self._udf_payload()
            return df.mapInPandas(
                lambda frames: _transform_partition(payload, frames),
                schema=schema)
        import pandas as pd
        pdf = df if isinstance(df, pd.DataFrame) else pd.DataFrame(df)
        return _append_predictions(self.model, self.params,
                                   self.feature_cols, self.output_cols(),
                                   pdf)

    # -- persistence (Spark ML write().save analog) -------------------------

    def save(self, path: str) -> None:
        import cloudpickle
        from .store import FilesystemStore
        st = self.store or FilesystemStore(path.rsplit("/", 1)[0] or ".")
        st.write_bytes(path, cloudpickle.dumps({
            "model": self.model, "params": self.params,
            "feature_cols": self.feature_cols,
            "label_cols": self.label_cols, "history": self.history,
        }))

    @staticmethod
    def load(path: str) -> "TpuTransformer":
        import cloudpickle
        from .store import FilesystemStore
        st = FilesystemStore(path.rsplit("/", 1)[0] or ".")
        d = cloudpickle.loads(st.read_bytes(path))
        return TpuTransformer(**d)
