"""horovod_tpu.spark — Spark cluster integration.

Reference surface (horovod/spark/__init__.py): ``run``/``run_elastic`` (fn
launchers over Spark barrier tasks) plus the Estimator layer
(spark/common/estimator.py, keras/estimator.py) with its Store abstraction
(spark/common/store.py).
"""

from ..spark_integration import run  # noqa: F401
from .elastic import run_elastic  # noqa: F401
from .store import (  # noqa: F401
    Store, FilesystemStore, LocalStore, shard_row_groups,
)
from .estimator import (  # noqa: F401
    HorovodTpuEstimator, TpuTransformer,
)

# Reference alias (spark/keras/estimator.py KerasEstimator &co. collapse to
# the one JAX estimator).
HorovodEstimator = HorovodTpuEstimator
