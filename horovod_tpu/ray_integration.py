"""Ray cluster integration — RayExecutor.

Reference: horovod/ray/runner.py:45 RayExecutor (one actor per slot,
ColocatedStrategy/PGStrategy placement-group packing, a Coordinator that
computes ranks and injects the rendezvous env, run/run_remote/execute API)
and the elastic variants (ray/elastic_v2.py).

TPU mapping: one Ray actor per TPU-VM host; each actor gets the same
HOROVOD_* rendezvous env the CLI launcher injects (runner/launch.py
_worker_env), initializes the runtime, and executes the user function.  Ray
placement groups with the ``TPU`` resource reserve whole hosts of a pod
slice, which is the analog of the reference's per-node GPU packing.

Ray is not a hard dependency: importing this module without ray installed
raises at executor construction with a clear message (the reference gates
identically on ``import ray``).
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

from . import config as _config
from .runner import hosts as _hosts
from .runner.http_server import RendezvousServer


def _require_ray():
    try:
        import ray  # noqa: F401
        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray_integration requires the 'ray' package "
            "(pip install ray); the core framework does not depend on it"
        ) from e


class RayExecutor:
    """Job executor backed by Ray actors (ray/runner.py:45 RayExecutor).

    Usage::

        executor = RayExecutor(num_workers=4, cpus_per_worker=1)
        executor.start()
        results = executor.run(train_fn, args=(lr,))
        executor.shutdown()
    """

    def __init__(self,
                 settings: Optional[dict] = None,
                 num_workers: int = 1,
                 cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 gpus_per_worker: int = 0,
                 tpu_per_worker: int = 0,
                 use_current_placement_group: bool = True):
        self.settings = settings or {}
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self.gpus_per_worker = gpus_per_worker
        self.tpu_per_worker = tpu_per_worker
        self.use_current_placement_group = use_current_placement_group
        self._workers: List[Any] = []
        self._rendezvous: Optional[RendezvousServer] = None

    def start(self,
              executable_cls: Optional[type] = None,
              executable_args: Optional[list] = None,
              extra_env_vars: Optional[Dict[str, str]] = None):
        """Create the actor pool and rendezvous (runner.py start)."""
        ray = _require_ray()
        self._rendezvous = RendezvousServer()
        port = self._rendezvous.start()
        addr = socket.gethostbyname(socket.gethostname())
        host_list = [_hosts.HostInfo(f"ray-slot-{i}", 1)
                     for i in range(self.num_workers)]
        assignments = _hosts.get_host_assignments(host_list,
                                                  self.num_workers)
        self._rendezvous.init(assignments)

        opts = {"num_cpus": self.cpus_per_worker}
        if self.use_gpu or self.gpus_per_worker:
            opts["num_gpus"] = self.gpus_per_worker or 1
        if self.tpu_per_worker:
            opts["resources"] = {"TPU": self.tpu_per_worker}
        if self.use_current_placement_group:
            # Run inside the caller's placement group when one exists
            # (ray/strategy.py pack semantics).
            pg = ray.util.get_current_placement_group()
            if pg is not None:
                from ray.util.scheduling_strategies import \
                    PlacementGroupSchedulingStrategy
                opts["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(placement_group=pg)

        @ray.remote(**opts)
        class Worker:
            def __init__(self, env: Dict[str, str]):
                os.environ.update(env)
                self._obj = None

            def setup(self, cls, args):
                self._obj = cls(*(args or []))
                return True

            def execute_fn(self, fn, *args, **kwargs):
                return fn(*args, **kwargs)

            def execute_obj(self, fn):
                return fn(self._obj)

        self._workers = []
        for slot in assignments:
            env = {
                _config.HOROVOD_RANK: str(slot.rank),
                _config.HOROVOD_SIZE: str(slot.size),
                _config.HOROVOD_LOCAL_RANK: str(slot.local_rank),
                _config.HOROVOD_LOCAL_SIZE: str(slot.local_size),
                _config.HOROVOD_CROSS_RANK: str(slot.cross_rank),
                _config.HOROVOD_CROSS_SIZE: str(slot.cross_size),
                _config.HOROVOD_RENDEZVOUS_ADDR: addr,
                _config.HOROVOD_RENDEZVOUS_PORT: str(port),
                # Derived from the dynamically-allocated rendezvous port so
                # concurrent executors on one head node don't collide.
                "HVD_TPU_COORDINATOR": f"{addr}:{port + 1}",
                **(extra_env_vars or {}),
            }
            self._workers.append(Worker.remote(env))
        if executable_cls is not None:
            ray.get([w.setup.remote(executable_cls, executable_args)
                     for w in self._workers])

    def run(self, fn: Callable, args: tuple = (), kwargs: Optional[dict] = None
            ) -> List[Any]:
        """Run fn(*args) on every worker, return per-rank results ordered by
        rank (runner.py run; fn never receives the executable object)."""
        ray = _require_ray()
        kwargs = kwargs or {}
        return ray.get([w.execute_fn.remote(fn, *args, **kwargs)
                        for w in self._workers])

    def run_remote(self, fn: Callable, args: tuple = (),
                   kwargs: Optional[dict] = None) -> List[Any]:
        """Async variant returning Ray object refs (runner.py run_remote)."""
        _require_ray()
        kwargs = kwargs or {}
        return [w.execute_fn.remote(fn, *args, **kwargs)
                for w in self._workers]

    def execute(self, fn: Callable) -> List[Any]:
        """Run fn(executable_obj) on every worker (runner.py execute;
        requires start(executable_cls=...))."""
        ray = _require_ray()
        return ray.get([w.execute_obj.remote(fn) for w in self._workers])

    def shutdown(self):
        ray = _require_ray()
        for w in self._workers:
            ray.kill(w)
        self._workers = []
        if self._rendezvous is not None:
            self._rendezvous.stop()
            self._rendezvous = None
