"""Spark cluster integration — run a training function on Spark executors.

Reference: horovod/spark/runner.py:200 ``horovod.spark.run`` (driver
service + per-task services, barrier-style rendezvous, then launch into the
running executors) and the Estimator layer (spark/common/estimator.py —
DataFrame→Parquet via a Store, petastorm readers, returns a Transformer).

TPU build scope: the ``run(fn, ...)`` entry point with the same rendezvous
flow (each Spark task becomes one rank; the driver hosts the HTTP
rendezvous KV store the tasks read, exactly like the CLI launcher).  The
Estimator/Store layer lives in ``horovod_tpu.spark`` (estimator.py,
store.py) — Parquet via pyarrow instead of petastorm.

PySpark is not a dependency of the core: everything gates on ``import
pyspark`` at call time.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, List, Optional

from . import config as _config
from .runner import hosts as _hosts
from .runner.http_server import RendezvousServer


def _require_pyspark():
    try:
        import pyspark  # noqa: F401
        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark_integration requires 'pyspark'; the core "
            "framework does not depend on it") from e


def run(fn: Callable,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None,
        extra_env_vars: Optional[dict] = None,
        verbose: int = 1) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks and return per-rank results
    ordered by rank (horovod.spark.run, spark/runner.py:200).

    The driver starts the rendezvous KV store; each barrier-mode task
    receives its rank env (HOROVOD_RANK/SIZE + rendezvous address), calls
    ``fn``, and ships its result back through Spark's collect."""
    pyspark = _require_pyspark()
    from pyspark import SparkContext

    sc = SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("no active SparkContext; create a SparkSession "
                           "before horovod_tpu.spark_integration.run")
    num_proc = num_proc or sc.defaultParallelism
    kwargs = kwargs or {}

    rendezvous = RendezvousServer()
    port = rendezvous.start()
    addr = socket.gethostbyname(socket.gethostname())
    host_list = [_hosts.HostInfo(f"spark-task-{i}", 1)
                 for i in range(num_proc)]
    rendezvous.init(_hosts.get_host_assignments(host_list, num_proc))
    extra = dict(extra_env_vars or {})

    def task_fn(_iterator):
        # Barrier task context: Spark gang-schedules all partitions or fails
        # fast when the cluster lacks slots (spark/runner.py start_timeout
        # guard); a plain mapPartitions would deadlock half-scheduled.
        from pyspark import BarrierTaskContext
        ctx = BarrierTaskContext.get()
        index = ctx.partitionId()
        # The jax.distributed coordinator runs inside rank 0's task on
        # whatever executor it landed on — rank 0 publishes its address via
        # the driver-hosted KV store and everyone else polls it (the CLI
        # launcher knows hostnames up front, runner/launch.py; Spark does
        # not).
        from .runner.http_server import KVStoreClient
        import time as _time
        client = KVStoreClient(addr, port)
        if index == 0:
            my_ip = socket.gethostbyname(socket.gethostname())
            client.put("spark", "coordinator",
                       f"{my_ip}:{port + 1}".encode())
            coordinator = f"{my_ip}:{port + 1}"
        else:
            deadline = _time.time() + 300
            coordinator = None
            while _time.time() < deadline:
                raw = client.get("spark", "coordinator")
                if raw:
                    coordinator = raw.decode()
                    break
                _time.sleep(0.2)
            if coordinator is None:
                raise RuntimeError(
                    "timed out waiting for rank 0's coordinator address")
        os.environ.update({
            _config.HOROVOD_RANK: str(index),
            _config.HOROVOD_SIZE: str(num_proc),
            _config.HOROVOD_LOCAL_RANK: "0",
            _config.HOROVOD_LOCAL_SIZE: "1",
            _config.HOROVOD_CROSS_RANK: str(index),
            _config.HOROVOD_CROSS_SIZE: str(num_proc),
            _config.HOROVOD_RENDEZVOUS_ADDR: addr,
            _config.HOROVOD_RENDEZVOUS_PORT: str(port),
            "HVD_TPU_COORDINATOR": coordinator,
            **extra,
        })
        yield index, fn(*args, **kwargs)

    try:
        rdd = sc.parallelize(range(num_proc), num_proc)
        results = rdd.barrier().mapPartitions(task_fn).collect()
    finally:
        rendezvous.stop()
    return [r for _, r in sorted(results)]


def __getattr__(name):
    # Lazy re-export: the Estimator layer lives in horovod_tpu.spark
    # (spark/estimator.py), but the old import path keeps working.
    if name in ("HorovodTpuEstimator", "TpuTransformer"):
        from .spark import estimator as _est
        return getattr(_est, name)
    raise AttributeError(name)
