"""Global runtime state + the init/info API surface.

The reference's equivalent is the ``extern "C"`` surface of
horovod/common/operations.cc:932-1405 (``horovod_init``, ``horovod_rank``,
``horovod_size``, ``horovod_local_rank``..., process-set CRUD, built/enabled
queries) reached from Python through the ctypes ``HorovodBasics`` wrapper
(common/basics.py:29,51), plus the background-thread bring-up of
``InitializeHorovodOnce`` (operations.cc:856).

The TPU build needs no background communication thread for the compiled data
plane — collectives live inside XLA programs — so ``init()`` reduces to:
resolve knobs, discover topology, (optionally) join the multi-process runtime
(``jax.distributed.initialize`` — the rendezvous analog of MPI_Init /
Gloo HTTP rendezvous, operations.cc:417-450), build the global device
``Mesh``, and register process sets.  The eager dispatch engine and its
negotiation core (the surviving part of the reference's controller) are
created lazily by ops/eager.py.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

from . import config as _config
from . import topology as _topology
from .utils import get_logger


class _GlobalState:
    """Singleton per process (reference: HorovodGlobalState, global_state.h:39)."""

    def __init__(self):
        self.lock = threading.RLock()
        self.initialized = False
        self.config: Optional[_config.Config] = None
        self.topology: Optional[_topology.Topology] = None
        self.mesh = None
        self.process_set_table = None
        self.eager_engine = None
        self.timeline = None
        self.param_manager = None
        self.elastic_enabled = False
        # JaxprReports published by the HVD_ANALYZE=1 trace-time hook
        # (analysis/hook.py); read via core.analysis_reports().  Survives
        # shutdown so post-run tooling (bench.py) can still read it.
        self.analysis_reports: List = []


_state = _GlobalState()


def _build_mesh(topo: _topology.Topology, cfg: _config.Config):
    import jax
    from jax.sharding import Mesh
    devices = topo.devices if topo.devices else list(jax.devices())
    return Mesh(np.asarray(devices), (cfg.mesh_axis,))


def _autotune_scope() -> str:
    """KV scope for autotune sync, namespaced by the negotiation generation:
    keys from a previous world incarnation (elastic reset) must never feed
    a fresh ParameterManager — a follower reading a stale candidate would
    explore a different fusion threshold than rank 0's new GP run."""
    return f"autotune@{os.environ.get('HVD_TPU_NEGOTIATION_GEN', '0')}"


def _maybe_join_distributed(cfg: _config.Config) -> None:
    """Join the multi-process JAX runtime when launched by horovodrun.

    The launcher injects HOROVOD_RANK/SIZE and the rendezvous address
    (runner/gloo_run.py:66-78 analog); we translate that into
    ``jax.distributed.initialize``, which plays the role of
    MPI_Init_thread / Gloo HTTP rendezvous in BackgroundThreadLoop
    (operations.cc:417-450)."""
    rank = os.environ.get(_config.HOROVOD_RANK)
    size = os.environ.get(_config.HOROVOD_SIZE)
    addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
    port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
    if rank is None or size is None or int(size) <= 1 or addr is None:
        return
    if os.environ.get("HOROVOD_ELASTIC") == "1":
        # Meet every peer incarnation of this world generation BEFORE
        # touching jax.distributed — a non-converging initialize aborts
        # the process (see elastic._await_world_at_init_barrier).  The
        # barrier may adopt a newer world, so re-read the slot env after.
        from .elastic import _await_world_at_init_barrier
        _await_world_at_init_barrier()
        rank = os.environ.get(_config.HOROVOD_RANK)
        size = os.environ.get(_config.HOROVOD_SIZE)
        if rank is None or size is None or int(size) <= 1:
            return
    # Must not touch the XLA backend (e.g. jax.devices/process_count) before
    # jax.distributed.initialize — probe the distributed client state instead.
    import jax
    from jax._src import distributed as _jdist
    if getattr(_jdist.global_state, "client", None) is not None:
        return  # already initialized by the user
    coordinator = os.environ.get(
        "HVD_TPU_COORDINATOR", f"{addr}:{int(port) + 1 if port else 9999}")
    # Bounded init: an elastic in-place reset can otherwise block the full
    # default 300 s inside initialize() waiting for a peer that is dead and
    # will re-rendezvous into a DIFFERENT world generation.  The elastic
    # retry loop handles the timeout (upgrade to a world refresh).
    init_timeout = int(float(os.environ.get(
        "HVD_TPU_DIST_INIT_TIMEOUT_S",
        os.environ.get(_config.HOROVOD_GLOO_TIMEOUT_SECONDS, "300"))))
    # A dead peer makes jax.distributed.shutdown's barrier hang the full
    # shutdown timeout before the client aborts the process; bound it so a
    # doomed survivor dies (and gets respawned into a fresh world) quickly.
    # Healthy same-world resets clear the barrier in well under a second.
    shutdown_timeout = int(float(os.environ.get(
        "HVD_TPU_DIST_SHUTDOWN_TIMEOUT_S", "60")))
    # Multi-process CPU worlds (the hermetic e2e test environment, and any
    # CPU-fallback deployment) need a cross-host collectives transport; on
    # jax 0.4.x the CPU backend refuses multiprocess computations unless
    # the gloo implementation is selected BEFORE the backend client is
    # created.  A no-op where unsupported/already-default, and irrelevant
    # to TPU backends (the flag only affects CPU clients).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    kwargs = dict(
        coordinator_address=coordinator,
        num_processes=int(size),
        process_id=int(rank),
        initialization_timeout=init_timeout,
        shutdown_timeout_seconds=shutdown_timeout,
    )
    try:
        jax.distributed.initialize(**kwargs)
    except TypeError:
        # Older jax (< 0.6) has no shutdown_timeout_seconds: the barrier
        # bound is lost (a doomed survivor hangs the full default before
        # aborting), but the world still forms — strictly better than not
        # initializing at all.
        kwargs.pop("shutdown_timeout_seconds")
        jax.distributed.initialize(**kwargs)


def init(comm: Optional[Sequence[int]] = None,
         process_sets=None) -> None:
    """Initialize the runtime (hvd.init analog, operations.cc:934 horovod_init).

    Args:
      comm: optional list of global ranks participating (reference: the
        ``ranks`` argument of horovod_init restricting the global communicator).
        Unsupported values raise — on TPU the job membership is fixed by the
        launcher/slice, matching horovod_init_multi_comm's constraints.
      process_sets: optional list of ``ProcessSet`` objects to register at
        init, like hvd.init(process_sets=[...]) (common/basics.py:51).
    """
    from . import process_sets as _ps

    with _state.lock:
        if _state.initialized:
            return
        from .analysis import hook as _analysis_hook
        if _analysis_hook.enabled():
            # Fresh world ⇒ fresh first-compile analysis generation: an
            # elastic re-init compiles new programs that deserve their own
            # check (analysis/hook.py generation()).
            _analysis_hook.reset()
            _state.analysis_reports = []
        cfg = _config.Config.from_env()
        if cfg.compilation_cache_dir:
            # Persistent XLA compilation cache: elastic world resizes and
            # relaunches re-trace every program (SURVEY.md §7 "hide latency
            # with compilation cache") — this makes the re-compile a disk hit.
            import jax
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  cfg.compilation_cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.5)
            except Exception as e:
                get_logger().warning("compilation cache setup failed: %s", e)
        _maybe_join_distributed(cfg)
        topo = _topology.detect(cfg)
        if comm is not None and list(comm) != list(range(topo.size)):
            raise ValueError(
                "horovod_tpu.init(comm=...) with a strict subset of ranks is "
                "not supported on TPU; use process sets instead "
                "(process_sets.add_process_set)")
        _state.config = cfg
        _state.topology = topo
        _state.mesh = _build_mesh(topo, cfg)
        _state.process_set_table = _ps.ProcessSetTable(topo.num_slots)
        if process_sets:
            for ps in process_sets:
                _state.process_set_table.register(ps)
        from .autotune import ParameterManager

        def _synced_decision(local_choice: int) -> int:
            """SynchronizeParameters: rank 0's converged threshold wins
            everywhere (rank-divergent thresholds would produce divergent
            fusion buckets → mismatched collectives)."""
            addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
            port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
            if topo.size <= 1 or topo.emulated or not addr or not port:
                return local_choice
            import json as _json
            import time as _time
            from .runner.http_server import KVStoreClient
            client = KVStoreClient(addr, int(port))
            scope = _autotune_scope()
            if topo.rank == 0:
                client.put(scope, "threshold",
                           _json.dumps({"threshold": local_choice}).encode())
                return local_choice
            deadline = _time.time() + 60
            while _time.time() < deadline:
                raw = client.get(scope, "threshold")
                if raw is not None:
                    return int(_json.loads(raw)["threshold"])
                _time.sleep(0.05)
            return local_choice

        search = cfg.autotune_search
        candidate_pub = candidate_fetch = None
        if cfg.autotune and search == "bayes" and topo.size > 1 and \
                not topo.emulated:
            # Multi-controller BO: rank 0 owns the GP and publishes each
            # round's exploration candidate through the rendezvous KV;
            # followers fetch it, so fusion buckets stay identical on
            # every rank (the reference's rank-0-tunes +
            # SynchronizeParameters design, parameter_manager.h).
            addr = os.environ.get(_config.HOROVOD_RENDEZVOUS_ADDR)
            port = os.environ.get(_config.HOROVOD_RENDEZVOUS_PORT)
            if not addr or not port:
                get_logger().warning(
                    "HOROVOD_AUTOTUNE_SEARCH=bayes needs the rendezvous KV "
                    "to sync candidates; falling back to the sweep")
                search = "sweep"
            else:
                import json as _json
                import time as _time
                from .runner.http_server import KVStoreClient
                _cli = KVStoreClient(addr, int(port))
                _scope = _autotune_scope()
                if topo.rank == 0:
                    def candidate_pub(round_, value):
                        _cli.put(_scope, f"cand/{round_}",
                                 _json.dumps(value).encode())
                else:
                    def candidate_fetch(round_):
                        deadline = _time.time() + 120
                        while _time.time() < deadline:
                            raw = _cli.get(_scope, f"cand/{round_}")
                            if raw is not None:
                                return float(_json.loads(raw))
                            _time.sleep(0.05)
                        from .exceptions import HorovodInternalError
                        raise HorovodInternalError(
                            f"timed out fetching autotune candidate for "
                            f"round {round_} from rank 0")
        _state.param_manager = ParameterManager(
            enabled=cfg.autotune,
            initial_threshold=cfg.fusion_threshold_bytes,
            log_path=cfg.autotune_log if topo.rank == 0 else None,
            decide_fn=_synced_decision,
            search=search,
            bayes_rounds=cfg.autotune_bayes_rounds,
            candidate_pub=candidate_pub,
            candidate_fetch=candidate_fetch)
        if cfg.timeline_path and topo.rank == 0:
            # Rank 0 writes the trace, like the reference coordinator
            # (HOROVOD_TIMELINE, operations.cc:1077).
            from .timeline import Timeline
            _state.timeline = Timeline(cfg.timeline_path,
                                       mark_cycles=cfg.timeline_mark_cycles,
                                       rank=topo.rank)
        _state.initialized = True
        get_logger().info(
            "horovod_tpu initialized: rank=%d size=%d local=%d/%d cross=%d/%d "
            "slots=%d mesh=%s", topo.rank, topo.size, topo.local_rank,
            topo.local_size, topo.cross_rank, topo.cross_size, topo.num_slots,
            tuple(_state.mesh.shape.items()))


def shutdown() -> None:
    """Tear down (horovod_shutdown, operations.cc)."""
    with _state.lock:
        if not _state.initialized:
            return
        if _state.timeline is not None:
            _state.timeline.close()
            _state.timeline = None
        _state.initialized = False
        _state.mesh = None
        _state.topology = None
        _state.process_set_table = None
        eng = _state.eager_engine
        if eng is not None and eng._negotiator is not None:
            eng._negotiator.close()  # stop flusher, ship pending records
        _state.eager_engine = None


atexit.register(shutdown)


def _require_init() -> _GlobalState:
    if not _state.initialized:
        raise ValueError(
            "horovod_tpu has not been initialized; call horovod_tpu.init() "
            "first (reference error string: operations.cc horovod_rank)")
    return _state


def is_initialized() -> bool:
    """horovod_is_initialized (operations.cc)."""
    return _state.initialized


def analysis_reports() -> list:
    """JaxprReports from the HVD_ANALYZE=1 trace-time checker (newest
    last).  Empty unless HVD_ANALYZE was set when step programs first
    compiled; see docs/static_analysis.md."""
    return list(_state.analysis_reports)


def start_timeline(file_path: str, mark_cycles: bool = False) -> None:
    """Runtime timeline start (horovod_start_timeline, operations.cc:1077)."""
    from .timeline import Timeline
    st = _require_init()
    if st.timeline is not None:
        st.timeline.close()
    st.timeline = Timeline(file_path, mark_cycles=mark_cycles,
                           rank=st.topology.rank)


def stop_timeline() -> None:
    """horovod_stop_timeline."""
    st = _require_init()
    if st.timeline is not None:
        st.timeline.close()
        st.timeline = None


def rank() -> int:
    """Global process rank (horovod_rank, operations.cc:1000)."""
    return _require_init().topology.rank


def size() -> int:
    """Global number of ranks (horovod_size)."""
    return _require_init().topology.size


def local_rank() -> int:
    """Rank within the node (horovod_local_rank)."""
    return _require_init().topology.local_rank


def local_size() -> int:
    """Ranks on this node (horovod_local_size)."""
    return _require_init().topology.local_size


def cross_rank() -> int:
    """Node index (horovod_cross_rank)."""
    return _require_init().topology.cross_rank


def cross_size() -> int:
    """Number of nodes (horovod_cross_size)."""
    return _require_init().topology.cross_size


def num_slots() -> int:
    """Total accelerator chips in the job — the mesh axis size.

    TPU extension: the reference's process==GPU identity splits on TPU where
    one process drives several chips; gradient averaging divides by this."""
    return _require_init().topology.num_slots


def local_slots() -> int:
    return _require_init().topology.local_slots


def mesh():
    """The global device mesh (jax.sharding.Mesh) over every chip."""
    return _require_init().mesh


def mesh_axis() -> str:
    return _require_init().config.mesh_axis


def is_homogeneous() -> bool:
    """horovod_is_homogeneous (operations.cc): equal slots per node."""
    return _require_init().topology.is_homogeneous


# ---------------------------------------------------------------------------
# Built/enabled feature queries (operations.cc:1050-1140 horovod_*_built /
# horovod_*_enabled).  The TPU build has exactly one backend — XLA collectives
# — so the legacy backend queries answer False and xla answers True; they are
# kept so reference scripts probing capabilities keep running.
# ---------------------------------------------------------------------------

def mpi_threads_supported() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    """TPU build: the XLA-collective backend is always present."""
    return True


def xla_enabled() -> bool:
    return True
