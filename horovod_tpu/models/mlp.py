"""MNIST-scale models (reference: examples/keras/keras_mnist.py,
examples/pytorch/pytorch_mnist.py — the smallest BASELINE config)."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Plain multi-layer perceptron over flattened features."""

    features: Sequence[int] = (128, 10)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


class MnistCNN(nn.Module):
    """The examples' small convnet (pytorch_mnist.py Net): two convs +
    two dense layers; expects NHWC images."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def create_mlp(features: Sequence[int] = (128, 10), **kwargs) -> MLP:
    return MLP(features=tuple(features), **kwargs)
