"""Model zoo for benchmarks and examples.

Mirrors the reference's benchmark surface (SURVEY.md §6): ResNet-50/101/152
(tf_cnn_benchmarks / synthetic benchmark models), an MNIST-scale MLP/CNN
(keras mnist examples), and transformer families (BERT-large / GPT-2) for the
BASELINE.json north-star configs.
"""

from .resnet import (  # noqa: F401
    ResNet, ResNet50, ResNet101, ResNet152, create_resnet50,
)

from .transformer import (  # noqa: F401
    Transformer, TransformerConfig, create_gpt2, create_bert, lm_loss,
    stack_block_params, unstack_block_params,
    GPT2_SMALL, GPT2_MEDIUM, GPT2_LARGE, BERT_BASE, BERT_LARGE,
)

from .mlp import MLP, MnistCNN, create_mlp  # noqa: F401
