"""Transformer family (GPT-2 / BERT) — TPU-first flax implementation.

Reference analog: the BASELINE.json north-star configs train BERT-large
(PyTorch DistributedOptimizer + gradient accumulation) and GPT-2 medium with
Adasum; the reference itself ships no model code beyond examples.  These
models are written for the MXU: bfloat16 matmuls with float32 layernorm/
softmax/loss islands, d_model/d_ff multiples of 128, optional
``jax.checkpoint`` rematerialization per block (HBM for FLOPs), and a
pluggable attention backend:

* ``seq_parallel=None``      — dense local attention (data-parallel only);
* ``seq_parallel='ring'``    — ring attention over the mesh axis
                               (parallel/ring.py), sequence sharded;
* ``seq_parallel='ulysses'`` — all_to_all head<->sequence exchange
                               (parallel/ulysses.py).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

import flax.linen as nn

from ..parallel.ring import (ring_attention, ring_attention_reference,
                             ring_flash_attention)
from ..parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_len: int = 1024
    causal: bool = True              # GPT style; False = BERT style
    dtype: Any = jnp.bfloat16
    axis_name: str = "hvd"
    seq_parallel: Optional[str] = None   # None|'ring'|'ring_striped'|'ulysses'
    attention_impl: Optional[str] = None  # None (dense) | 'flash' (Pallas)
    remat: bool = False
    scan_layers: bool = False  # lax.scan over blocks: ~L x faster compile
    # Mixture-of-experts FFN (parallel/moe.py).  moe_experts > 0 replaces
    # the dense FFN with a top-k-routed MoE in every ``moe_every``-th block
    # (GShard alternation).  expert_axis names the mesh axis experts are
    # sharded over (params carry the GLOBAL [E, ...] expert dim; shard them
    # with in_specs on that axis) — None keeps experts replicated.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_every: int = 2
    expert_axis: Optional[str] = None


# Benchmark-standard configurations.
GPT2_SMALL = TransformerConfig(num_layers=12, num_heads=12, d_model=768,
                               d_ff=3072)
GPT2_MEDIUM = TransformerConfig(num_layers=24, num_heads=16, d_model=1024,
                                d_ff=4096)
GPT2_LARGE = TransformerConfig(num_layers=36, num_heads=20, d_model=1280,
                               d_ff=5120)
BERT_BASE = TransformerConfig(vocab_size=30522, num_layers=12, num_heads=12,
                              d_model=768, d_ff=3072, max_len=512,
                              causal=False)
BERT_LARGE = TransformerConfig(vocab_size=30522, num_layers=24, num_heads=16,
                               d_model=1024, d_ff=4096, max_len=512,
                               causal=False)


class SelfAttention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, S, _ = x.shape
        head_dim = cfg.d_model // cfg.num_heads
        dense = partial(nn.DenseGeneral, dtype=cfg.dtype,
                        kernel_init=nn.initializers.normal(0.02))
        qkv = dense(features=(3, cfg.num_heads, head_dim), axis=-1,
                    name="qkv")(x)
        q, k, v = (qkv[:, :, i] for i in range(3))  # [B, S, H, Dh]
        if cfg.attention_impl not in (None, "flash"):
            raise ValueError(
                f"unknown attention_impl {cfg.attention_impl!r}; "
                f"expected None or 'flash'")
        use_flash = cfg.attention_impl == "flash"

        def local_flash(q, k, v, *, causal, scale=None):
            from ..parallel.flash import flash_attention
            return flash_attention(q, k, v, causal=causal, scale=scale)

        if cfg.seq_parallel in ("ring", "ring_striped"):
            # flash composes with the ring since round 5: the per-hop
            # block math runs in the Pallas kernel and the hops combine
            # by the (out, lse) logsumexp merge (ring_flash_attention).
            ring_fn = ring_flash_attention if use_flash else ring_attention
            out = ring_fn(q, k, v, axis_name=cfg.axis_name,
                          causal=cfg.causal,
                          striped=cfg.seq_parallel == "ring_striped")
        elif cfg.seq_parallel == "ulysses":
            out = ulysses_attention(
                q, k, v, axis_name=cfg.axis_name, causal=cfg.causal,
                attention_fn=local_flash if use_flash else None)
        elif use_flash:
            out = local_flash(q, k, v, causal=cfg.causal)
        else:
            out = ring_attention_reference(q, k, v, causal=cfg.causal)
        return dense(features=cfg.d_model, axis=(-2, -1), name="proj")(out)


class Block(nn.Module):
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = partial(nn.LayerNorm, dtype=jnp.float32, epsilon=1e-5)
        h = ln(name="ln1")(x)
        x = x + SelfAttention(cfg, name="attn")(h.astype(cfg.dtype))
        h = ln(name="ln2")(x)
        if self.use_moe:
            return x + self._moe_ffn(h.astype(cfg.dtype))
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, name="fc1",
                     kernel_init=nn.initializers.normal(0.02))(
                         h.astype(cfg.dtype))
        h = nn.gelu(h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, name="fc2",
                     kernel_init=nn.initializers.normal(0.02))(h)
        return x + h

    def _moe_ffn(self, h):
        """Top-k expert-parallel FFN (parallel/moe.py).  Params hold the
        expert dim at its LOCAL extent: the full E at init / replicated
        apply, E / n under shard_map with the expert dim sharded over
        cfg.expert_axis.  The aux load-balancing loss is sown into the
        "losses" collection — apply with ``mutable=["losses"]`` and add
        ``sum(jax.tree.leaves(mutated["losses"]))`` to the objective."""
        from jax import lax
        from ..parallel.moe import expert_parallel_ffn
        cfg = self.cfg
        if cfg.expert_axis:
            try:
                n = lax.axis_size(cfg.expert_axis)
            except NameError as e:
                raise ValueError(
                    f"expert_axis={cfg.expert_axis!r} is not bound — "
                    "initialize with expert_axis=None (params carry the "
                    "global [E, ...] expert dim) and shard them via "
                    "in_specs on the expert axis under shard_map; see "
                    "docs/moe.md") from e
        else:
            n = 1
        if cfg.moe_experts % max(n, 1):
            raise ValueError(f"moe_experts ({cfg.moe_experts}) must divide "
                             f"by the {cfg.expert_axis!r} axis size ({n})")
        e_local = cfg.moe_experts // n
        init = nn.initializers.normal(0.02)
        gate = self.param("moe_gate", init,
                          (cfg.d_model, cfg.moe_experts), jnp.float32)
        w_in = self.param("moe_w_in", init,
                          (e_local, cfg.d_model, cfg.d_ff), jnp.float32)
        w_out = self.param("moe_w_out", init,
                           (e_local, cfg.d_ff, cfg.d_model), jnp.float32)
        b, s, d = h.shape
        res = expert_parallel_ffn(
            h.reshape(b * s, d), gate,
            w_in.astype(cfg.dtype), w_out.astype(cfg.dtype),
            axis_name=cfg.expert_axis, top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor)
        self.sow("losses", "moe_aux", res.aux_loss)
        return res.out.reshape(b, s, d)


class _ScanBlock(nn.Module):
    """Block adapted to the scan calling convention (carry, xs) ->
    (carry, ys); the real work stays in :class:`Block`."""
    cfg: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, _):
        return Block(self.cfg, use_moe=self.use_moe, name="block")(x), None


class Transformer(nn.Module):
    """Decoder-only (causal=True, GPT) or encoder (causal=False, BERT)
    producing token logits (LM head ties the embedding)."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, positions=None, predict_positions=None):
        """``predict_positions`` ([B, K] int32, BERT MLM only): apply the
        final layernorm + LM head ONLY at those K gathered positions and
        return [B, K, vocab] logits.  At 15 % masking the full-sequence
        head wastes ~6x its FLOPs and (at vocab 30k, f32) dominates logit
        HBM traffic — this is the standard max_predictions_per_seq
        formulation of BERT pretraining."""
        cfg = self.cfg
        B, S = tokens.shape
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       embedding_init=nn.initializers.normal(0.02),
                       dtype=cfg.dtype, name="wte")
        if positions is None:
            if cfg.seq_parallel == "ring_striped":
                # Striped layout: this shard holds global tokens
                # [idx, idx+n, idx+2n, ...].
                from ..parallel.ring import striped_positions
                positions = striped_positions(
                    S, axis_name=cfg.axis_name)[None, :]
            else:
                positions = jnp.arange(S)[None, :]
                if cfg.seq_parallel is not None:
                    # Block-sharded: this shard holds global tokens
                    # [idx*S, (idx+1)*S) — offset the position embedding or
                    # every shard but the first would silently embed 0..S-1.
                    from jax import lax as _lax
                    positions = positions + _lax.axis_index(
                        cfg.axis_name) * S
        pos_emb = nn.Embed(cfg.max_len, cfg.d_model,
                           embedding_init=nn.initializers.normal(0.01),
                           dtype=cfg.dtype, name="wpe")(positions)
        x = emb(tokens) + pos_emb
        if cfg.scan_layers:
            # One traced block, lax.scan'd over stacked [L, ...] params:
            # the HLO carries ONE block body instead of num_layers copies,
            # which divides XLA compile time by ~the depth — the lever
            # that brought GPT-2-medium's remote compile (>10 min through
            # the relay, TODO.md r4) back into budget.  Param tree changes
            # shape (blocks/block/... stacked) — stack_block_params
            # migrates unrolled checkpoints.
            if cfg.moe_experts > 0 and cfg.moe_every != 1:
                raise ValueError(
                    "scan_layers needs homogeneous blocks; interleaved "
                    "MoE (moe_every > 1) must use scan_layers=False")
            inner = _ScanBlock
            if cfg.remat:
                # prevent_cse is scan's job here (jax.checkpoint docs).
                inner = nn.remat(_ScanBlock, prevent_cse=False)
            blocks = nn.scan(
                inner,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
            )(cfg, use_moe=cfg.moe_experts > 0, name="blocks")
            x, _ = blocks(x, None)
        else:
            block = Block
            if cfg.remat:
                block = nn.remat(Block)  # jax.checkpoint: HBM for FLOPs
            for i in range(cfg.num_layers):
                use_moe = (cfg.moe_experts > 0
                           and i % cfg.moe_every == cfg.moe_every - 1)
                x = block(cfg, use_moe=use_moe, name=f"block_{i}")(x)
        if predict_positions is not None:
            x = jnp.take_along_axis(
                x, predict_positions[..., None].astype(jnp.int32), axis=1)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        # Tied LM head (GPT-2 convention); f32 logits for a stable loss.
        logits = emb.attend(x.astype(cfg.dtype)).astype(jnp.float32)
        return logits


def stack_block_params(params, num_layers: int):
    """Migrate an UNROLLED checkpoint (``block_0``..``block_{L-1}``) to the
    ``scan_layers`` layout (``blocks/block/...`` with leaves stacked on a
    leading layer axis).  Non-block entries (wte/wpe/ln_f) pass through.
    The inverse direction is ``unstack_block_params``."""
    import flax
    import numpy as np
    flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(params))
    out, grouped = {}, {}
    for k, v in flat.items():
        if k[0].startswith("block_"):
            grouped.setdefault(k[1:], {})[int(k[0][len("block_"):])] = v
        else:
            out[k] = v
    for rest, by_layer in grouped.items():
        if sorted(by_layer) != list(range(num_layers)):
            raise ValueError(
                f"checkpoint has layers {sorted(by_layer)} for "
                f"{'/'.join(rest)}, expected 0..{num_layers - 1}")
        out[("blocks", "block") + rest] = np.stack(
            [by_layer[i] for i in range(num_layers)])
    return flax.traverse_util.unflatten_dict(out)


def unstack_block_params(params):
    """scan_layers checkpoint -> unrolled layout (inverse of
    :func:`stack_block_params`)."""
    import flax
    flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(params))
    out = {}
    for k, v in flat.items():
        if k[:2] == ("blocks", "block"):
            for i in range(v.shape[0]):
                out[(f"block_{i}",) + k[2:]] = v[i]
        else:
            out[k] = v
    return flax.traverse_util.unflatten_dict(out)


def lm_loss(logits, targets, mask=None):
    """Token cross-entropy in f32 (BERT MLM or GPT next-token; caller shifts
    targets for causal LM)."""
    import optax
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if mask is not None:
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(losses)


def create_gpt2(size: str = "medium", **overrides) -> Transformer:
    """Factories default ``scan_layers=True``: one traced block lax.scan'd
    over stacked params compiles ~num_layers x faster at identical step
    numerics (24-layer measurement: 59.7 -> 5.2 s CPU compile, StableHLO
    943 -> 137 kB) — the fix for GPT-2-medium's >10 min remote compile.
    Pass ``scan_layers=False`` for the unrolled block_i param layout;
    ``stack_block_params``/``unstack_block_params`` convert checkpoints.
    Caveat: per-TENSOR gradient methods see stacked leaves as one tensor —
    Adasum in particular computes its projection coefficients per leaf.
    Keep the reference's per-layer granularity by passing
    ``per_layer_stacked`` to ``hvd.adasum_delta_step`` (it computes one
    coefficient pair per layer slice; examples/gpt2_adasum.py shows the
    pattern), or fall back to ``scan_layers=False``."""
    base = {"small": GPT2_SMALL, "medium": GPT2_MEDIUM,
            "large": GPT2_LARGE}[size]
    overrides.setdefault("scan_layers", True)
    return Transformer(dataclasses.replace(base, **overrides))


def create_bert(size: str = "large", **overrides) -> Transformer:
    """See :func:`create_gpt2` for the ``scan_layers`` default."""
    base = {"base": BERT_BASE, "large": BERT_LARGE}[size]
    overrides.setdefault("scan_layers", True)
    return Transformer(dataclasses.replace(base, **overrides))
