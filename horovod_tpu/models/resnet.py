"""ResNet family (v1.5) in flax — the framework's flagship benchmark model.

Reference analog: the reference benchmarks Horovod with tf_cnn_benchmarks /
Keras applications ResNet-50 (docs/benchmarks.rst:27-43,
examples/tensorflow2/tensorflow2_synthetic_benchmark.py:25-80 uses
``applications.ResNet50``).  The model itself is not reference code — this is
a standard ResNet-v1.5 written TPU-first:

* NHWC layout + channels padded to MXU-friendly multiples;
* bfloat16 activations/weights with float32 batch-norm statistics and loss
  (the canonical TPU mixed-precision recipe);
* optional cross-rank synchronized batch norm via ``axis_name`` (the
  hvd.SyncBatchNormalization analog, sync_batch_norm.py:22).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride on the 3x3, not the 1x1 (what tf_cnn_benchmarks runs).
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 use_bias=False, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None  # set to "hvd" for sync batch norm
    block_cls: ModuleDef = BottleneckBlock

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype, padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32,
                       axis_name=self.axis_name if train else None)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])


def create_resnet50(num_classes: int = 1000, dtype=jnp.bfloat16,
                    sync_bn: bool = False):
    return ResNet50(num_classes=num_classes, dtype=dtype,
                    axis_name="hvd" if sync_bn else None)
