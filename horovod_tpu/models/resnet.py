"""ResNet family (v1.5) in flax — the framework's flagship benchmark model.

Reference analog: the reference benchmarks Horovod with tf_cnn_benchmarks /
Keras applications ResNet-50 (docs/benchmarks.rst:27-43,
examples/tensorflow2/tensorflow2_synthetic_benchmark.py:25-80 uses
``applications.ResNet50``).  The model itself is not reference code — this is
a standard ResNet-v1.5 written TPU-first:

* NHWC layout + channels padded to MXU-friendly multiples;
* bfloat16 activations/weights with float32 batch-norm statistics and loss
  (the canonical TPU mixed-precision recipe);
* optional cross-rank synchronized batch norm via ``axis_name`` (the
  hvd.SyncBatchNormalization analog, sync_batch_norm.py:22).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn

ModuleDef = Any


def _space_to_depth(x):
    """(N, H, W, C) -> (N, H/2, W/2, 4C); depth flattened as (di, dj, c)."""
    n, h, w, c = x.shape
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


class SpaceToDepthStem(nn.Module):
    """The stem's 7x7/stride-2 conv re-indexed as a 4x4/stride-1 conv on
    2x2 space-to-depth input (the MLPerf TPU ResNet trick).

    Identical math: y[p,q] = sum_{u,v} w[u,v] x[2p+u-2, 2q+v-2] becomes,
    with u = 2A + di (A in 0..3, di in 0..1) and s2d rows m holding
    original rows 2m+di, a 4-tap conv over m = p-1..p+2, i.e. kernel 4,
    stride 1, padding (1, 2).  The kernel is stored in the ORIGINAL
    (7, 7, C, F) layout (checkpoint-compatible with the naive conv),
    zero-padded to 8x8 and regrouped per call — 12K floats, free next to
    the conv itself.  Why bother: the naive stem conv runs at 224^2
    spatial with 3 input channels — the worst MXU shape in the net and
    the largest single fusion in the round-2 profile; the re-indexed conv
    runs at 112^2 with 12 channels."""
    features: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.shape[1] % 2 or x.shape[2] % 2:
            raise ValueError("SpaceToDepthStem requires even H and W, got "
                             f"{x.shape}; use the naive stem (fast_stem="
                             "False) for odd extents")
        c = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, c, self.features), jnp.float32)
        k = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))
        k = k.reshape(4, 2, 4, 2, c, self.features)
        k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                  self.features)
        return jax.lax.conv_general_dilated(
            _space_to_depth(x).astype(self.dtype), k.astype(self.dtype),
            window_strides=(1, 1), padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _max_pool_3x3s2(x):
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")


@jax.custom_vjp
def max_pool_eq_grad(x):
    """3x3/stride-2 SAME max pool whose backward pass is written as
    elementwise equality gathers instead of XLA's ``select_and_scatter``
    (1.4 ms/step in the round-2 ResNet profile; no MXU, poorly tiled on
    TPU).  Tie semantics differ deliberately: ``select_and_scatter``
    routes the gradient to the FIRST max of a window, this routes 1/n to
    EACH of n tied maxima — the gradient sum is preserved, which is the
    property training cares about."""
    return _max_pool_3x3s2(x)


def _mp_fwd(x):
    if x.shape[1] % 2 or x.shape[2] % 2:
        # The parity-gather backward assumes SAME padding (0, 1) per
        # spatial dim, which holds only for even extents.
        raise ValueError("max_pool_eq_grad requires even H and W, got "
                         f"{x.shape}; use nn.max_pool for odd extents")
    y = _max_pool_3x3s2(x)
    return y, (x, y)


def _mp_bwd(res, g):
    x, y = res
    n, h, w, c = x.shape
    oh, ow = y.shape[1], y.shape[2]
    neg = jnp.asarray(-jnp.inf, x.dtype)
    # SAME for k=3, s=2, even H: pad lo 0, hi 1.
    xp = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)), constant_values=neg)

    # Tie counts per window, at output resolution (padded -inf never
    # equals y: every window contains at least one real element).
    cnt = jnp.zeros(y.shape, jnp.float32)
    for u in range(3):
        for v in range(3):
            win = jax.lax.slice(xp, (0, u, v, 0),
                                (n, u + 2 * oh - 1, v + 2 * ow - 1, c),
                                (1, 2, 2, 1))
            cnt = cnt + (win == y).astype(jnp.float32)
    gn = g.astype(jnp.float32) / cnt

    def row_gathers(a):
        """a at output rows -> (A, B) at input rows: A[i] = a[i//2]
        (valid for all i: window floor(i/2) always covers row i),
        B[i] = a[i//2 - 1] (covers row i only for even i >= 2)."""
        rep = jnp.repeat(a, 2, axis=1)[:, :h]
        shifted = jnp.pad(rep, ((0, 0), (2, 0), (0, 0), (0, 0)))[:, :h]
        return rep, shifted

    def col_gathers(a):
        rep = jnp.repeat(a, 2, axis=2)[:, :, :w]
        shifted = jnp.pad(rep, ((0, 0), (0, 0), (2, 0), (0, 0)))[:, :, :w]
        return rep, shifted

    row_even = (jnp.arange(h) % 2 == 0) & (jnp.arange(h) >= 2)
    col_even = (jnp.arange(w) % 2 == 0) & (jnp.arange(w) >= 2)
    row_masks = (jnp.ones(h, bool), row_even)
    col_masks = (jnp.ones(w, bool), col_even)

    grad = jnp.zeros(x.shape, jnp.float32)
    ga_rows, gy_rows = row_gathers(gn), row_gathers(y)
    for ri in range(2):
        g_r, y_r = ga_rows[ri], gy_rows[ri]
        g_rc, y_rc = col_gathers(g_r), col_gathers(y_r)
        for ci in range(2):
            mask = (row_masks[ri][None, :, None, None]
                    & col_masks[ci][None, None, :, None])
            eq = (x == y_rc[ci]) & mask
            grad = grad + jnp.where(eq, g_rc[ci], 0.0)
    return (grad.astype(x.dtype),)


max_pool_eq_grad.defvjp(_mp_fwd, _mp_bwd)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = self.act(y)
        # v1.5: stride on the 3x3, not the 1x1 (what tf_cnn_benchmarks runs).
        y = self.conv(self.filters, (3, 3), self.strides, use_bias=False)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 use_bias=False, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None  # set to "hvd" for sync batch norm
    block_cls: ModuleDef = BottleneckBlock
    s2d_stem: bool = False       # space-to-depth re-indexed stem conv
    eq_pool_grad: bool = False   # maxpool backward without select_and_scatter
    fused_bn: bool = True        # f32-stats / bf16-apply folded batch norm

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype, padding="SAME")
        if self.fused_bn:
            # FusedBatchNorm (sync_batch_norm.py): f32 statistics, folded
            # per-channel scale/offset applied in the activation dtype, so
            # the BN+ReLU+add epilogue fuses with its conv neighbors
            # instead of a standalone f32 normalize chain (PERF_r02's
            # BN-chain headroom; same param/stat tree as flax BatchNorm).
            from ..sync_batch_norm import FusedBatchNorm
            norm = partial(FusedBatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                           axis_name=self.axis_name if train else None)
        else:
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5, dtype=jnp.float32,
                           axis_name=self.axis_name if train else None)
        x = x.astype(self.dtype)
        if self.s2d_stem:
            x = SpaceToDepthStem(self.num_filters, dtype=self.dtype,
                                 name="conv_init")(x)
        else:
            # use_bias=False: the bias feeds straight into BN, which
            # subtracts it right back out (and it kept the param tree
            # from matching SpaceToDepthStem's).
            x = conv(self.num_filters, (7, 7), (2, 2), use_bias=False,
                     name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if self.eq_pool_grad:
            x = max_pool_eq_grad(x)
        else:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i,
                                   strides=strides, conv=conv, norm=norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])


def migrate_pre_r3_checkpoint(params):
    """Migrate a checkpoint saved before the stem went bias-free.

    Earlier rounds' ``conv_init`` carried a bias that BN immediately
    subtracted out; dropping it changed the param tree, so old checkpoints
    no longer restore directly.  This deletes the redundant ``bias`` leaf
    (a no-op if already absent) and returns a tree matching the current
    model.  Safe because the bias never affected the function computed."""
    import flax
    flat = flax.traverse_util.flatten_dict(flax.core.unfreeze(params))
    flat = {k: v for k, v in flat.items()
            if not (k[-1] == "bias" and "conv_init" in k)}
    return flax.traverse_util.unflatten_dict(flat)


def create_resnet50(num_classes: int = 1000, dtype=jnp.bfloat16,
                    sync_bn: bool = False, fast_stem: bool = False,
                    fused_bn: bool = True):
    """``fast_stem=True`` enables the two TPU stem optimizations
    (SpaceToDepthStem + max_pool_eq_grad); ``fused_bn`` (default) uses the
    f32-stats/bf16-apply folded batch norm — same math, same param tree."""
    return ResNet50(num_classes=num_classes, dtype=dtype,
                    axis_name="hvd" if sync_bn else None,
                    s2d_stem=fast_stem, eq_pool_grad=fast_stem,
                    fused_bn=fused_bn)
