"""Finding / Rule data model shared by the AST linter and the jaxpr checker.

The reference catches the classic SPMD failure — ranks submitting different
collective sequences — at RUNTIME, in the coordinator's negotiation phase
(controller.cc ComputeResponseList: "Mismatched allreduce" stall warnings).
hvdlint reports the same class of bug STATICALLY, so every finding carries
the shape the negotiation error would have had: what diverges, where, and
how to fix it before the job wedges.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Rule:
    """One lint rule: stable ID, severity, rationale, and a fix hint that is
    attached verbatim to every finding it produces."""

    id: str
    severity: str
    summary: str
    fix_hint: str


# ---------------------------------------------------------------------------
# Rule catalogue.  HVD0xx = source-level (AST) rules; HVD1xx = trace-level
# (jaxpr) rules; HVD000 is the analyzer's own loud-but-graceful degradation
# channel (syntax errors, unreadable files).  docs/static_analysis.md renders
# this table; tests/test_hvdlint.py exercises each AST rule on a seeded
# violation corpus.
# ---------------------------------------------------------------------------

RULES: Dict[str, Rule] = {r.id: r for r in [
    Rule("HVD000", ERROR,
         "analysis failure: the file could not be parsed (syntax error or "
         "unreadable); reported as a finding instead of crashing the linter",
         "fix the syntax error, or exclude the file from the lint paths"),
    Rule("HVD001", ERROR,
         "collective call guarded by rank-dependent control flow — only a "
         "subset of ranks reaches the collective, the rest wait forever "
         "(the deadlock Horovod's negotiation phase detects at runtime)",
         "move the collective outside the `if rank() == ...` block; every "
         "rank must execute the same collective sequence"),
    Rule("HVD002", ERROR,
         "collective inside a try/except whose handler swallows the "
         "exception — a rank that raises skips the collective while the "
         "others block in it",
         "re-raise inside the handler (or raise HorovodInternalError) so "
         "either every rank completes the collective or the job tears down"),
    Rule("HVD003", ERROR,
         "unseeded `random`/`np.random` global-state call inside a traced "
         "function — each rank traces different constants, producing "
         "divergent compiled programs and divergent model state",
         "use jax.random with an explicitly shared PRNGKey, or a seeded "
         "np.random.RandomState(seed)/default_rng(seed)"),
    Rule("HVD004", WARNING,
         "host side effect (print/open/io_callback) inside a traced step "
         "function — runs at trace time only (or adds a host round-trip), "
         "and ordered callbacks can serialize ranks",
         "use jax.debug.print for traced values, or move host I/O outside "
         "the step function"),
    Rule("HVD005", WARNING,
         ".block_until_ready()/jax.device_get inside the step function — "
         "forces a device→host sync on the hot path, breaking XLA's "
         "compute/collective overlap",
         "fetch results outside the step; sync once per iteration batch at "
         "most"),
    Rule("HVD006", ERROR,
         "collective names an axis that no enclosing mesh/shard_map/pmap in "
         "this file declares — fails with an unbound-axis NameError at "
         "trace time (or silently reduces over the wrong group)",
         "use the declared mesh axis name (hvd.mesh_axis(), default 'hvd') "
         "or add the axis to the mesh"),
    Rule("HVD007", WARNING,
         "mutation of closed-over Python state inside a traced function — "
         "happens once at trace time, not per step, and diverges across "
         "ranks that trace independently",
         "thread state through function arguments/returns (carry it in the "
         "step's pytree) instead of mutating captured objects"),
    Rule("HVD008", ERROR,
         "wall-clock call (time.time/perf_counter/datetime.now) inside a "
         "traced function — baked in as a trace-time constant that differs "
         "per rank and per retrace",
         "pass timestamps in as arguments, or measure outside the traced "
         "step"),
    Rule("HVD009", ERROR,
         "collective or KV-transport call inside a bare `except:` or an "
         "`except Exception: pass` — the swallowed-fault antipattern: a "
         "dropped control-plane error is invisible (a preemption watcher "
         "that eats its scan error polls a ghost forever; a swallowed "
         "collective desynchronizes ranks)",
         "count the error into metrics, log it, back off and retry "
         "(serve/replica.watch_preemption is the model), or re-raise"),
    Rule("HVD010", ERROR,
         "reused-or-ambient PRNG in serving code: a jax.random.PRNGKey/"
         "fold_in inside serve/ seeded from the wall clock or a "
         "rank/request-independent constant — clock seeds break the "
         "replay/failover exactness contract (the same request resampled "
         "elsewhere draws different tokens), constant seeds hand every "
         "request the same stream (batch-position correlations the "
         "batched==single-given-the-same-key contract forbids)",
         "derive every serving key from the request's seed "
         "(sampling.seq_key folds (seed, sample_index); per-token keys "
         "fold the position) so draws are reproducible and "
         "request-independent"),
    Rule("HVD011", WARNING,
         "blocking device sync (jax.device_get / .block_until_ready() / "
         "np.asarray on a device value) inside a `with self._lock` "
         "region in serve/ — the static sibling of hvdrace's HVD201: "
         "every other request thread needing that lock stalls for the "
         "full device round-trip, and a wedged device wedges the whole "
         "control plane",
         "snapshot what the sync needs under the lock, release it, then "
         "pull the value to host (the engine's decode loop fetches "
         "outside its critical sections — that is the model)"),
    # -- lock-order / thread-lifecycle (hvdrace static) rules ---------------
    Rule("HVD200", ERROR,
         "lock-order cycle: two code paths acquire the same pair of locks "
         "in opposite orders (the AB/BA deadlock shape the serve batcher/"
         "metrics pair shipped with once) — if the paths ever run "
         "concurrently the threads deadlock holding each other's lock",
         "pick ONE global order for the pair and restructure the inner "
         "acquisition out of the outer critical section (sample state "
         "under one lock, act on it after release); declare the intended "
         "order with '# hvdrace: order=A<B' so inversions keep firing"),
    Rule("HVD201", WARNING,
         "blocking call (KV/HTTP request, subprocess, time.sleep, "
         "Thread.join, jit-compiled step) while holding a lock — every "
         "other thread needing that lock stalls for the call's full "
         "latency, and a hung transport wedges the whole control plane",
         "move the blocking call outside the critical section: snapshot "
         "what it needs under the lock, release, then block"),
    Rule("HVD202", ERROR,
         "callback/user-hook invoked while holding a lock — the callee is "
         "arbitrary code that may take its own lock (the exact shape of "
         "the batcher on_shed → metrics-lock half of the PR 3 AB/BA "
         "deadlock) or re-enter the calling object",
         "collect the callbacks to fire under the lock, release it, then "
         "invoke them (batcher.get_admission's expired-list finally "
         "block is the model)"),
    Rule("HVD203", ERROR,
         "non-daemon thread spawned with no join() on any stop/close "
         "path — interpreter exit blocks on it forever, and an exception "
         "between spawn and a sole in-line join leaks it",
         "pass daemon=True (loop threads that poll a stop Event), or "
         "store the handle and join it from every stop()/close() path"),
    # -- lock-witness (hvdrace runtime, HVD_SANITIZE=1) rules ---------------
    Rule("HVD210", ERROR,
         "runtime lock-order inversion: the witness observed lock B "
         "acquired while holding A after an earlier A-while-holding-B "
         "acquisition — a live demonstration of an HVD200 cycle",
         "fix the acquisition order (see HVD200); the finding carries "
         "both acquisition sites"),
    Rule("HVD211", ERROR,
         "Condition.wait()/Event.wait() with no timeout while holding a "
         "second lock — the wait releases only its own lock, so the "
         "other lock is held until a wakeup that may never come",
         "wait with a bounded timeout and re-check, or release the "
         "second lock before waiting"),
    # -- hvdmem HBM liveness / donation / budget rules ----------------------
    Rule("HVD300", WARNING,
         "donatable-but-undonated: a jit/pjit argument whose shape+dtype "
         "matches an output and is dead after its last read, yet absent "
         "from donate_argnums — XLA holds both the old and the new "
         "buffer live, doubling that value's steady-state footprint "
         "(donating the KV cache halves decode memory)",
         "add the argument's index to donate_argnums so XLA aliases the "
         "update in place (and never read the donated value after the "
         "call)"),
    Rule("HVD301", ERROR,
         "donated-then-used: a value passed into a donated argument slot "
         "is referenced again after the call — the buffer was consumed "
         "by donation and the read raises at runtime (the PR 4 "
         "donated-then-consumed cache hazard, caught statically instead "
         "of via is_deleted)",
         "rebind the name to the call's result (cache, out = fn(cache, "
         "...)) or drop the donation for a value that must survive"),
    Rule("HVD302", ERROR,
         "peak-exceeds-budget: the estimated peak live footprint (or the "
         "serve pool's bytes_per_block * num_blocks + weight bytes) "
         "exceeds HVD_MEM_BUDGET_BYTES / the probed device HBM — the "
         "program OOMs the chip at runtime, discovered only after "
         "minutes of compile",
         "shrink the pool (HVD_SERVE_NUM_BLOCKS), quantize KV blocks "
         "(HVD_SERVE_KV_DTYPE=int8), donate dead inputs, or raise the "
         "budget if the probe undershoots the real HBM"),
    Rule("HVD303", WARNING,
         "silent-upcast blowup: bf16/f16 values flow through ops that "
         "promote them to f32, widening the live set 2x — the "
         "f32-serving-island footprint made visible (intentional f32 "
         "islands under HVD_SERVE_DTYPE/documented knobs should be "
         "small; a whole param/activation set widening is a leak)",
         "keep the wide island minimal (layernorm-style), or store/"
         "compute in the narrow dtype and cast per-tile inside the "
         "kernel"),
    Rule("HVD304", WARNING,
         "fusion-buffer overshoot: a fused flat-buffer bucket exceeds "
         "the tensor-fusion threshold knob (HOROVOD_FUSION_THRESHOLD) — "
         "the bucket transiently costs its full size twice (memcpy-in + "
         "collective result), past what the knob budgeted",
         "lower the bucket size or raise the threshold knowingly; "
         "autotune (HOROVOD_AUTOTUNE=1) finds the sweet spot"),
    # -- hvdshard sharding / communication-plan rules -----------------------
    Rule("HVD400", WARNING,
         "implicit resharding: a value produced under one sharding is "
         "consumed under another — GSPMD silently inserts the transfer "
         "(an all-gather + re-slice in the worst case), invisible in "
         "the source and paid every step",
         "reshard once, explicitly (with_sharding_constraint / rebind "
         "the constrained result to a new name), or align the producer "
         "and consumer specs so nothing moves"),
    Rule("HVD401", ERROR,
         "comm-budget overshoot: the program's estimated per-step wire "
         "bytes (payload x communicator group size, summed over every "
         "collective plus implicit reshards) exceed "
         "HVD_COMM_BUDGET_BYTES — or the DCN share exceeds the "
         "stricter HVD_COMM_DCN_BUDGET_BYTES sub-budget; the step is "
         "communication-bound before it ever runs",
         "shard to keep traffic on ICI (cross-host axes are the slow "
         "fabric), fuse/batch collectives, or raise the budget "
         "knowingly"),
    Rule("HVD402", WARNING,
         "replicated-large-operand: a multi-MB operand rides fully "
         "replicated next to peers sharded over a declared mesh axis "
         "that divides its leading dim — every device holds (and every "
         "transfer mails) a full copy a known sharding would split "
         "(the comm analogue of HVD300's undonated buffer)",
         "shard the operand over the peer axis (P(axis) on dim 0) and "
         "let the consumer gather the slices it needs"),
    Rule("HVD403", ERROR,
         "collective over an axis no mesh declares, or one flat "
         "collective mixing ICI and DCN axes — the first reduces over "
         "a process set that does not exist in this deployment "
         "(HVD102's negotiation mismatch, multi-host edition); the "
         "second moves the whole payload at DCN speed instead of the "
         "hierarchical ICI-then-DCN decomposition",
         "declare the axis on the mesh, or split the collective "
         "hierarchically: reduce over the ICI axis first, then the "
         "DCN axis (hierarchical_allreduce is the model)"),
    Rule("HVD404", WARNING,
         "declared-but-never-communicated mesh axis: an axis of size "
         "> 1 that no collective and no sharding spec ever names — "
         "dead parallelism: the mesh reserves N x the chips and the "
         "program replicates the same work on all of them",
         "drop the axis from the mesh, or actually shard/reduce over "
         "it (in_specs / out_specs / a collective naming it)"),
    # -- trace-level (jaxpr) rules -----------------------------------------
    Rule("HVD100", ERROR,
         "the step function failed to trace — the jaxpr checker reports the "
         "exception as a finding instead of crashing the caller",
         "reproduce with jax.make_jaxpr(step)(*args) and fix the trace "
         "error"),
    Rule("HVD101", ERROR,
         "collective primitive names a mesh axis that is not declared by "
         "the enclosing mesh/shard_map (the static form of reducing over a "
         "communicator that does not exist)",
         "declare the axis on the mesh, or fix the axis_name argument"),
    Rule("HVD102", WARNING,
         "lax.cond branches carry different collective signatures "
         "(primitive/axis/shape/dtype sequence) — if the predicate ever "
         "diverges across ranks, some ranks issue collectives the others "
         "never post: the static analogue of Horovod's negotiation "
         "mismatch",
         "hoist collectives out of the cond, or make both branches issue "
         "the identical collective sequence (the unused branch can reduce "
         "zeros); safe only if the predicate is provably replicated"),
]}


@dataclasses.dataclass
class Finding:
    """One analyzer finding, renderable as text or JSON."""

    rule: str
    path: str            # file path, or a logical label for jaxpr findings
    line: int            # 1-based; 0 for whole-file / whole-program findings
    col: int
    message: str
    severity: str = ""
    fix_hint: str = ""
    suppressed: bool = False
    # "lint" | "jaxpr" | "race" | "witness" | "mem" | "comm"
    source: str = "lint"

    def __post_init__(self):
        rule = RULES.get(self.rule)
        if rule is not None:
            if not self.severity:
                self.severity = rule.severity
            if not self.fix_hint:
                self.fix_hint = rule.fix_hint

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}" if self.line else self.path
        sup = " (suppressed)" if self.suppressed else ""
        return (f"{loc}: {self.rule} [{self.severity}]{sup} {self.message}\n"
                f"    fix: {self.fix_hint}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def unsuppressed(findings) -> list:
    return [f for f in findings if not f.suppressed]


def rule_selected(rule: str, select=(), ignore=()) -> bool:
    """Shared --select/--ignore filter for every analyzer pass.  Tokens
    match exactly OR as prefixes (``--select HVD3`` runs the whole
    HVD3xx family), uniformly across lint/race/mem; ``select`` wins when
    both are given (the usual linter contract), and applies to every
    rule including HVD000 analysis failures."""
    def hit(tokens) -> bool:
        return any(rule == tok or rule.startswith(tok) for tok in tokens)
    if select:
        return hit(select)
    return not hit(ignore)
