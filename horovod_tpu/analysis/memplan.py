"""hvdmem — static HBM liveness, donation, and budget analysis (HVD3xx).

Every subsystem in this repo ultimately fights over one resource: device
memory.  The paged KV cache (PR 4) exists because slot reservations
overshot it, quantized KV blocks (PR 7) exist because bf16 blocks filled
it, and the donated-then-consumed cache hazard (PR 4) was a *runtime*
crash whose shape is fully visible statically.  vLLM answers the same
questions dynamically (block accounting at admission) and XLA answers
them opaquely (buffer-donation aliasing at compile time); hvdmem makes
both **auditable before a program ever OOMs a chip**.

Two cooperating halves, mirroring hvdlint's AST/jaxpr split:

* **jaxpr liveness walk** (``measure_closed_jaxpr`` /
  ``measure_step_fn``): per-eqn live-set byte accounting — last-use
  analysis over eqn invars/outvars, sub-jaxprs recursed (``scan`` bodies
  carry-aware and counted ONCE, never multiplied by trip count; ``cond``
  branches max'd; single-eqn ``pjit``/``shard_map`` wrappers unwrapped so
  per-shard avals — already divided by the mesh axis sizes for the
  sharded dims — are what gets accounted) — producing a
  ``peak_live_bytes`` estimate plus a per-primitive allocation breakdown.
  Rules on top of the walk: HVD300 (donatable-but-undonated), HVD302
  (peak exceeds ``HVD_MEM_BUDGET_BYTES`` / probed HBM), HVD303
  (silent bf16→f32 upcast blowup), HVD304 (fusion bucket overshooting
  the tensor-fusion threshold knob).
* **AST rules** (``analyze_source`` / ``analyze_paths``, the CLI
  ``--mem`` pass): the source-level shapes of the same hazards — HVD300
  (a jit'ted local function that functionally updates a parameter via
  ``.at[...]`` and returns the update, with no ``donate_argnums`` at the
  jit site) and HVD301 (a variable passed into a donated argument slot
  and *read again* after the call — the PR 4 donated-then-consumed cache
  bug caught statically instead of at runtime via ``is_deleted``).
  Stdlib-only (ast), same pragma/suppression contract as hvdlint.

Surfacing matches the PR 2 collective census: ``HVD_ANALYZE=1`` runs the
walk on every first compile (analysis/hook.py), the result lands in
``core.analysis_reports()`` (``JaxprReport.memory``), in the active
timeline as ``MEMORY_CENSUS`` counter events, and in bench.py's JSON
record under ``memory_census``.  The serve engine folds its *actual*
allocation plan — ``paged_block_bytes() * num_blocks`` + weight bytes —
into the same budget check at construction and exposes the result as
``kv_headroom_bytes`` on ``healthz``/``/metrics`` (docs/serving.md).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, \
    Set, Tuple

from .findings import Finding, rule_selected

# Bytes below which an undonated-but-donatable arg is noise, not a
# finding: donating a [B]-sized token vector saves nothing, donating a
# KV pool halves steady-state decode footprint.
DONATION_MIN_BYTES = 1 << 20

def upcast_min_bytes_default() -> int:
    """Floor for one bf16/f16 → f32 promotion to count toward HVD303
    (HVD_MEM_UPCAST_MIN_BYTES, bytes): the f32 layernorm islands the
    serve adapter runs on purpose are a few KB; a whole activation/param
    set silently widening is MBs.  Read per call like the sibling knobs
    so a malformed value degrades to the default instead of breaking the
    package import."""
    try:
        return int(os.environ.get("HVD_MEM_UPCAST_MIN_BYTES",
                                  str(8 << 20)))
    except ValueError:
        return 8 << 20


def fusion_threshold_bytes() -> int:
    """The tensor-fusion bucket bound (HOROVOD_FUSION_THRESHOLD, bytes —
    the same knob the eager fusion path sizes its flat buffers by)."""
    try:
        return int(os.environ.get("HOROVOD_FUSION_THRESHOLD",
                                  str(128 << 20)))
    except ValueError:
        return 128 << 20


def device_budget_bytes() -> Optional[int]:
    """The HBM budget the HVD302 check measures against:
    ``HVD_MEM_BUDGET_BYTES`` when set, else the probed per-device memory
    limit, else None (no budget known — HVD302 stays silent)."""
    env = os.environ.get("HVD_MEM_BUDGET_BYTES", "")
    if env:
        try:
            return int(env)
        except ValueError:
            return None
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = int(stats.get("bytes_limit", 0))
            return limit or None
    except Exception:
        pass
    return None


def params_bytes(tree: Any) -> int:
    """Total bytes of a param/array pytree (0 for None/array-free)."""
    if tree is None:
        return 0
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return 0
    total = 0
    for leaf in leaves:
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class MemReport:
    """Result of one liveness walk (or one pool-budget check)."""

    label: str
    peak_live_bytes: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    # prim name -> {"count": eqn executions (scan bodies counted once),
    # "bytes": output bytes those eqns allocate}
    by_primitive: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    budget_bytes: Optional[int] = None
    headroom_bytes: Optional[int] = None
    upcast_f32_bytes: int = 0
    findings: List[Finding] = dataclasses.field(default_factory=list)

    #: Duck-type compatibility with JaxprReport consumers (bench.py reads
    #: ``reports[-1].census``): a MemReport carries no collective census.
    @property
    def census(self) -> dict:
        return {}

    @property
    def memory(self) -> dict:
        return self.to_dict()

    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "peak_live_bytes": int(self.peak_live_bytes),
            "input_bytes": int(self.input_bytes),
            "output_bytes": int(self.output_bytes),
            "budget_bytes": self.budget_bytes,
            "headroom_bytes": self.headroom_bytes,
            "upcast_f32_bytes": int(self.upcast_f32_bytes),
            "by_primitive": {k: dict(v)
                             for k, v in sorted(self.by_primitive.items())},
        }


# ---------------------------------------------------------------------------
# Jaxpr liveness walk
# ---------------------------------------------------------------------------

def _aval_bytes(aval: Any) -> int:
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        return int(size) * int(dtype.itemsize)
    except Exception:
        return 0


def sharding_divisor(sharding: Any) -> int:
    """How many ways a NamedSharding-style sharding splits an array:
    the product of the mesh axis sizes named by its spec ("divided by
    mesh axis sizes for the sharded dims").  1 for replicated/unknown."""
    try:
        spec = getattr(sharding, "spec", None)
        mesh = getattr(sharding, "mesh", None)
        if spec is None or mesh is None:
            return 1
        shape = dict(mesh.shape)
        div = 1
        for entry in spec:
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            for axis in axes:
                div *= int(shape.get(axis, 1))
        return max(div, 1)
    except Exception:
        return 1


class _LivenessWalker:
    """Simulates allocation order over a jaxpr: outputs of an eqn are
    allocated before its inputs can die (XLA cannot free an operand mid-
    op), values die after their last read unless pinned (non-donated
    top-level inputs: the caller still holds them, XLA cannot reuse the
    buffers), sub-programs contribute their internal transient (their
    peak beyond the boundary values the outer level already counts)."""

    def __init__(self, report: MemReport, fusion_threshold: int,
                 upcast_min: int):
        import jax
        self._var = jax.core.Var
        self.report = report
        self.fusion_threshold = fusion_threshold
        self.upcast_min = upcast_min
        self._upcast_sites = 0
        self._first_upcast = ""

    # -- helpers ------------------------------------------------------------

    def _as_jaxpr(self, obj):
        import jax
        if isinstance(obj, jax.core.ClosedJaxpr):
            return obj.jaxpr
        if isinstance(obj, jax.core.Jaxpr):
            return obj
        return None

    def _sub_jaxprs(self, eqn) -> List[Any]:
        subs: List[Any] = []
        for val in eqn.params.values():
            for item in (val if isinstance(val, (tuple, list)) else (val,)):
                j = self._as_jaxpr(item)
                if j is not None:
                    subs.append(j)
        return subs

    def _boundary_bytes(self, j) -> int:
        return sum(_aval_bytes(v.aval)
                   for v in list(j.constvars) + list(j.invars))

    def _transient(self, sub) -> int:
        """A sub-program's peak beyond its boundary values (its invars /
        constvars alias outer operands already counted as live)."""
        j = self._as_jaxpr(sub)
        if j is None:
            return 0
        peak = self.walk(j, pinned=frozenset(), divisors={})
        return max(0, peak - self._boundary_bytes(j))

    def _eqn_transient(self, eqn) -> int:
        name = eqn.primitive.name
        if name == "cond":
            # Branches are exclusive at runtime: peak takes the MAX.
            return max((self._transient(b)
                        for b in eqn.params.get("branches", ())), default=0)
        if name == "scan":
            # Carry-aware: the body's working set exists once per
            # iteration, sequentially — its transient counts ONCE, never
            # multiplied by trip count (the stacked xs/ys already sit in
            # the outer eqn's operands/results).
            return self._transient(eqn.params.get("jaxpr"))
        if name in ("while", "while_loop"):
            return max(self._transient(eqn.params.get("cond_jaxpr")),
                       self._transient(eqn.params.get("body_jaxpr")))
        return max((self._transient(s) for s in self._sub_jaxprs(eqn)),
                   default=0)

    # -- per-eqn rule checks ------------------------------------------------

    def _check_upcast(self, eqn) -> None:
        """HVD303 input gathering: a bf16/f16 value promoted to f32/f64,
        element count preserved, past the size floor."""
        if eqn.primitive.name != "convert_element_type":
            return
        try:
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
        except (IndexError, AttributeError):
            return
        src_dt = str(getattr(src, "dtype", ""))
        dst_dt = str(getattr(dst, "dtype", ""))
        if src_dt not in ("bfloat16", "float16") or \
                dst_dt not in ("float32", "float64"):
            return
        out_bytes = _aval_bytes(dst)
        if out_bytes < self.upcast_min:
            return
        self.report.upcast_f32_bytes += out_bytes
        self._upcast_sites += 1
        if not self._first_upcast:
            self._first_upcast = (
                f"{src_dt}{tuple(getattr(src, 'shape', ()))} -> {dst_dt}")

    def _check_fusion(self, eqn) -> None:
        """HVD304: a rank-1 flat-buffer concatenation bigger than the
        tensor-fusion threshold knob — the fused-bucket overshoot that
        doubles a step's transient footprint past what the knob
        promises."""
        if eqn.primitive.name != "concatenate" or not eqn.outvars:
            return
        out = eqn.outvars[0].aval
        if len(getattr(out, "shape", (0, 0))) != 1:
            return
        out_bytes = _aval_bytes(out)
        if out_bytes > self.fusion_threshold:
            self.report.findings.append(Finding(
                rule="HVD304", path=self.report.label, line=0, col=0,
                source="mem",
                message=f"fused flat buffer of {out_bytes} bytes exceeds "
                        f"the tensor-fusion threshold "
                        f"({self.fusion_threshold} bytes, "
                        f"HOROVOD_FUSION_THRESHOLD) — the bucket overshoot "
                        f"costs its full size twice (gather-in + "
                        f"collective result) at peak"))

    def finish_upcast(self) -> None:
        """HVD303 fires when the promotions dominate: total upcast bytes
        at least a quarter of the peak ("promotes the whole live set"),
        not the few param-sized bf16→f32 accumulation casts every
        mixed-precision backward pass legitimately performs."""
        up = self.report.upcast_f32_bytes
        if self._upcast_sites and \
                up * 4 >= max(self.report.peak_live_bytes, 1):
            self.report.findings.append(Finding(
                rule="HVD303", path=self.report.label, line=0, col=0,
                source="mem",
                message=f"{self._upcast_sites} low-precision value(s) "
                        f"promoted to f32 for {up} bytes — "
                        f"{100 * up // max(self.report.peak_live_bytes, 1)}"
                        f"% of the {self.report.peak_live_bytes}-byte "
                        f"peak (first: {self._first_upcast}): the "
                        f"silent-upcast footprint — the live set widens "
                        f"2x through these ops"))

    # -- the walk -----------------------------------------------------------

    def walk(self, j, pinned, divisors: Dict[Any, int]) -> int:
        """Returns this jaxpr's peak live bytes, counting its boundary
        (constvars + invars) as live at entry.  ``pinned`` vars never die
        (non-donated top-level inputs); ``divisors`` divide specific
        invars' bytes (pjit shardings at the top level)."""
        j = self._as_jaxpr(j)
        if j is None:
            return 0

        def vbytes(v) -> int:
            return _aval_bytes(v.aval) // max(divisors.get(v, 1), 1)

        last_use: Dict[Any, int] = {}
        for i, eqn in enumerate(j.eqns):
            for v in eqn.invars:
                if isinstance(v, self._var):
                    last_use[v] = i
        outset = {v for v in j.outvars if isinstance(v, self._var)}
        live: Dict[Any, int] = {}
        live_bytes = 0
        for v in list(j.constvars) + list(j.invars):
            if v not in live:
                live[v] = vbytes(v)
                live_bytes += live[v]
        peak = live_bytes
        for i, eqn in enumerate(j.eqns):
            transient = self._eqn_transient(eqn)
            out_bytes = 0
            for v in eqn.outvars:
                b = vbytes(v)
                live_bytes += b - live.get(v, 0)
                live[v] = b
                out_bytes += b
            entry = self.report.by_primitive.setdefault(
                eqn.primitive.name, {"count": 0, "bytes": 0})
            entry["count"] += 1
            entry["bytes"] += out_bytes
            self._check_upcast(eqn)
            self._check_fusion(eqn)
            peak = max(peak, live_bytes + transient)
            for v in list(eqn.invars) + list(eqn.outvars):
                if not isinstance(v, self._var):
                    continue
                if v in outset or v in pinned:
                    continue
                if last_use.get(v, i) <= i:
                    live_bytes -= live.pop(v, 0)
        return peak


def _unwrap_wrappers(jaxpr, donated: Optional[Tuple[bool, ...]],
                     divisors: Dict[Any, int]):
    """Descend through single-eqn ``pjit``/``shard_map`` wrappers so the
    accounting sees the program the chip sees: a shard_map body's avals
    are PER-SHARD (bytes already divided by the mesh axis sizes for the
    sharded dims), and a pjit wrapper carries the donation flags
    (``donated_invars``) and shardings the caller compiled with.
    Explicitly passed donation wins over discovered flags."""
    while True:
        if jaxpr.constvars or len(jaxpr.eqns) != 1:
            return jaxpr, donated, divisors
        eqn = jaxpr.eqns[0]
        name = eqn.primitive.name
        if name not in ("pjit", "shard_map"):
            return jaxpr, donated, divisors
        if list(eqn.invars) != list(jaxpr.invars) or \
                list(eqn.outvars) != list(jaxpr.outvars):
            return jaxpr, donated, divisors
        inner = eqn.params.get("jaxpr")
        import jax
        if isinstance(inner, jax.core.ClosedJaxpr):
            inner = inner.jaxpr
        if inner is None or len(inner.invars) != len(jaxpr.invars):
            return jaxpr, donated, divisors
        if name == "pjit":
            if donated is None:
                flags = eqn.params.get("donated_invars")
                if flags is not None:
                    donated = tuple(bool(f) for f in flags)
            shardings = eqn.params.get("in_shardings") or ()
            divisors = {
                v: sharding_divisor(s)
                for v, s in zip(inner.invars, shardings)
                if sharding_divisor(s) > 1}
        else:  # shard_map: per-shard avals — nothing further to divide
            divisors = {}
        jaxpr = inner


def donated_invar_flags(args: Sequence[Any],
                        donate_argnums: Optional[Sequence[int]]
                        ) -> Optional[List[bool]]:
    """Expand per-ARGUMENT donation indices into per-INVAR (flattened
    pytree leaf) flags — ``jax.make_jaxpr`` flattens each argument into
    its leaves, so a donated pytree argument donates every one of its
    leaf invars, not just the leaf at its argument index."""
    if donate_argnums is None:
        return None
    import jax
    nums = set(int(i) for i in donate_argnums)
    flags: List[bool] = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        flags.extend([i in nums] * n)
    return flags


def measure_closed_jaxpr(closed_jaxpr,
                         *,
                         label: str = "<jaxpr>",
                         donate_argnums: Optional[Sequence[int]] = None,
                         donated_invars: Optional[Sequence[bool]] = None,
                         budget_bytes: Optional[int] = None,
                         fusion_threshold: Optional[int] = None,
                         upcast_min_bytes: Optional[int] = None,
                         donation_min_bytes: int = DONATION_MIN_BYTES
                         ) -> MemReport:
    """Liveness-walk an already-traced program.

    Donation info comes from (highest precedence first)
    ``donated_invars`` (one bool per flattened invar — what
    ``donated_invar_flags`` computes from call args), ``donate_argnums``
    (positions into the INVAR list; only correct when every argument is
    a single leaf), or a top-level ``pjit`` wrapper's own
    ``donated_invars`` (``jax.make_jaxpr(jitted_fn)`` preserves them).
    With donation info available, HVD300 fires for each non-donated
    input that matches an output's shape+dtype (≥ ``donation_min_bytes``)
    — the args whose donation would let XLA alias the update in place.
    ``budget_bytes`` defaults to ``device_budget_bytes()``; when known,
    HVD302 fires if the peak estimate exceeds it.
    """
    report = MemReport(label=label)
    jaxpr = closed_jaxpr.jaxpr if hasattr(closed_jaxpr, "jaxpr") \
        else closed_jaxpr
    donated: Optional[Tuple[bool, ...]] = None
    if donated_invars is not None:
        if len(donated_invars) == len(jaxpr.invars):
            donated = tuple(bool(f) for f in donated_invars)
        # Length mismatch (static/closed-over args): donation unknown —
        # stay conservative rather than mislabel leaves.
    elif donate_argnums is not None:
        nums = set(int(i) for i in donate_argnums)
        donated = tuple(i in nums for i in range(len(jaxpr.invars)))
    jaxpr, donated, divisors = _unwrap_wrappers(jaxpr, donated, divisors={})

    walker = _LivenessWalker(
        report,
        fusion_threshold if fusion_threshold is not None
        else fusion_threshold_bytes(),
        upcast_min_bytes if upcast_min_bytes is not None
        else upcast_min_bytes_default())

    def in_bytes(v) -> int:
        return _aval_bytes(v.aval) // max(divisors.get(v, 1), 1)

    # Top-level constvars (closure-captured weights under make_jaxpr) are
    # held by the caller exactly like non-donated invars: never freeable.
    if donated is None:
        pinned = frozenset(list(jaxpr.invars) + list(jaxpr.constvars))
    else:
        pinned = frozenset(
            [v for v, d in zip(jaxpr.invars, donated) if not d]
            + list(jaxpr.constvars))
    report.input_bytes = sum(in_bytes(v) for v in jaxpr.invars)
    report.output_bytes = sum(
        _aval_bytes(getattr(v, "aval", None)) for v in jaxpr.outvars)
    report.peak_live_bytes = walker.walk(jaxpr, pinned, divisors)
    walker.finish_upcast()

    # HVD300: donatable-but-undonated args (donation info required —
    # without it every input is conservatively pinned and no claim about
    # the caller's intent can be made).
    if donated is not None:
        out_avals = {}
        for v in jaxpr.outvars:
            aval = getattr(v, "aval", None)
            if aval is not None:
                key = (tuple(getattr(aval, "shape", ())),
                       str(getattr(aval, "dtype", "?")))
                out_avals.setdefault(key, 0)
                out_avals[key] += 1
        outset = {v for v in jaxpr.outvars}
        # Already-donated invars consume their matching output first:
        # XLA aliases each donated buffer to one output, so that output
        # is spoken for and cannot justify donating a second arg.
        for v, d in zip(jaxpr.invars, donated):
            if not d:
                continue
            key = (tuple(getattr(v.aval, "shape", ())),
                   str(getattr(v.aval, "dtype", "?")))
            if out_avals.get(key):
                out_avals[key] -= 1
        for idx, (v, d) in enumerate(zip(jaxpr.invars, donated)):
            if d or v in outset:
                continue
            b = _aval_bytes(v.aval)
            if b < donation_min_bytes:
                continue
            key = (tuple(getattr(v.aval, "shape", ())),
                   str(getattr(v.aval, "dtype", "?")))
            if out_avals.get(key):
                out_avals[key] -= 1
                report.findings.append(Finding(
                    rule="HVD300", path=label, line=0, col=0, source="mem",
                    message=f"arg {idx} ({key[1]}{key[0]}, {b} bytes) "
                            f"matches an output's shape+dtype but is not "
                            f"donated — donating it lets XLA alias the "
                            f"update in place instead of holding both "
                            f"copies live"))

    budget = budget_bytes if budget_bytes is not None \
        else device_budget_bytes()
    report.budget_bytes = budget
    if budget is not None:
        report.headroom_bytes = int(budget) - int(report.peak_live_bytes)
        if report.headroom_bytes < 0:
            report.findings.append(Finding(
                rule="HVD302", path=label, line=0, col=0, source="mem",
                message=f"estimated peak live footprint "
                        f"{report.peak_live_bytes} bytes exceeds the "
                        f"memory budget {budget} bytes "
                        f"(HVD_MEM_BUDGET_BYTES / probed HBM) by "
                        f"{-report.headroom_bytes} bytes"))
    return report


def measure_step_fn(fn: Callable, args: Sequence[Any] = (),
                    kwargs: Optional[dict] = None, *,
                    label: Optional[str] = None,
                    donate_argnums: Optional[Sequence[int]] = None,
                    axis_env: Optional[Sequence[Tuple[str, int]]] = None,
                    **measure_kwargs) -> MemReport:
    """Trace ``fn(*args, **kwargs)`` and liveness-walk it.  Never raises
    on the user's program: a trace failure comes back as an HVD100-style
    empty report (the jaxpr checker owns trace-failure reporting)."""
    import jax
    name = label or getattr(fn, "__name__", None) or "step"
    kw = kwargs or {}
    try:
        traced = jax.make_jaxpr(
            lambda *a: fn(*a, **kw),
            axis_env=[tuple(e) for e in axis_env] if axis_env else None,
        )(*args)
    except Exception:
        return MemReport(label=name)
    return measure_closed_jaxpr(
        traced, label=name,
        donated_invars=donated_invar_flags(args, donate_argnums),
        **measure_kwargs)


# ---------------------------------------------------------------------------
# Pool-budget check (the serve engine's construction-time HVD302)
# ---------------------------------------------------------------------------

def check_pool_budget(label: str, pool_bytes: int, weight_bytes: int,
                      budget: Optional[int] = None) -> MemReport:
    """Verify a concrete allocation plan — the BlockManager pool
    (``paged_block_bytes() * num_blocks``) plus the replica's weight
    bytes — against the budget.  Returns a MemReport whose
    ``headroom_bytes`` is what the engine exposes as
    ``kv_headroom_bytes``; an HVD302 finding when the plan overshoots."""
    budget = budget if budget is not None else device_budget_bytes()
    report = MemReport(label=label,
                       peak_live_bytes=int(pool_bytes) + int(weight_bytes),
                       input_bytes=int(weight_bytes),
                       output_bytes=int(pool_bytes),
                       budget_bytes=budget)
    if budget is not None:
        report.headroom_bytes = int(budget) - report.peak_live_bytes
        if report.headroom_bytes < 0:
            report.findings.append(Finding(
                rule="HVD302", path=label, line=0, col=0, source="mem",
                message=f"KV pool ({pool_bytes} bytes) + weights "
                        f"({weight_bytes} bytes) = "
                        f"{report.peak_live_bytes} bytes exceeds the "
                        f"memory budget {budget} bytes by "
                        f"{-report.headroom_bytes} bytes — shrink "
                        f"HVD_SERVE_NUM_BLOCKS or quantize KV blocks "
                        f"(HVD_SERVE_KV_DTYPE=int8)"))
    return report


def publish_report(report: MemReport) -> None:
    """Log findings, append to ``core.analysis_reports()``, and chart
    the memory census on the active timeline — the exact surfacing the
    PR 2 collective census uses.  Never raises."""
    from ..utils import get_logger
    log = get_logger()
    for f in report.findings:
        log.warning("hvdmem: %s", f.format())
    try:
        from .. import core as _core
        _core._state.analysis_reports.append(report)
        tl = _core._state.timeline
        if tl is not None:
            tl.memory_census(report.label, report.to_dict())
    except Exception as e:  # pragma: no cover - publication is best-effort
        log.warning("hvdmem: could not publish report: %s", e)


# ---------------------------------------------------------------------------
# AST half (the CLI --mem pass): HVD300 / HVD301 source shapes
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jit", "pjit"}


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jax.pjit(...)`` / bare ``jit(...)``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr in _JIT_NAMES
    if isinstance(f, ast.Name):
        return f.id in _JIT_NAMES
    return False


def _donated_indices(call: ast.Call) -> Optional[Set[int]]:
    """Literal ``donate_argnums`` of a jit call: a set of ints, empty set
    for an explicit ``()``, or None when absent / non-literal (the author
    either did not think about donation — HVD300's cue — or computed it
    dynamically, which the linter cannot follow)."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        val = kw.value
        if isinstance(val, ast.Constant) and isinstance(val.value, int):
            return {val.value}
        if isinstance(val, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for elt in val.elts:
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, int):
                    out.add(elt.value)
                else:
                    return set()  # partially dynamic: donation intended
            return out
        return set()  # non-literal donate_argnums: donation intended
    return None


def _target_key(node: ast.AST) -> Optional[Tuple[str, str]]:
    """Stable key for a Name or a ``self.attr`` attribute (the two
    binding shapes the dataflow tracks)."""
    if isinstance(node, ast.Name):
        return ("n", node.id)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name):
        return ("a", f"{node.value.id}.{node.attr}")
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Root Name of a Subscript/Attribute/Call chain (``cache["k"].at``
    → ``cache``; ``dict(cache)`` → first tainted arg's root)."""
    while True:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            node = node.value
        elif isinstance(node, ast.Call):
            if node.args:
                node = node.args[0]
            else:
                return None
        else:
            return None


def _fn_updates_and_returns_param(fn: ast.AST) -> Optional[int]:
    """Does this function functionally update (``.at[...].set/add/...``)
    a value rooted at one of its parameters and return the update?
    Returns the offending line (the first ``.at`` use) or None.

    A ``lax.scan`` body threading its carry is NOT flagged: the carry is
    the *body's* parameter, not the jitted function's — taint is scoped
    per function."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
        return None
    args = fn.args
    params = {a.arg for a in list(args.args) + list(args.kwonlyargs)
              + list(args.posonlyargs)}
    tainted = set(params)
    updated: Set[str] = set()
    update_line: Optional[int] = None
    body = fn.body if isinstance(fn.body, list) else [ast.Return(fn.body)]

    def expr_is_update(node: ast.AST) -> bool:
        """``<tainted>...at[...].<set|add|...>(...)`` chain."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return False
        sub = node.func.value
        if not isinstance(sub, ast.Subscript):
            return False
        at = sub.value
        if not (isinstance(at, ast.Attribute) and at.attr == "at"):
            return False
        root = _root_name(at.value)
        return root in tainted

    # Nested function defs own their parameters' taint — skip their
    # bodies (a scan/cond body updating ITS carry is the clean idiom).
    def _walk_skip_nested(root: ast.AST):
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.append(child)

    nodes: List[ast.AST] = []
    for stmt in body:
        nodes.extend(_walk_skip_nested(stmt))
    nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                              getattr(n, "col_offset", 0)))

    for node in nodes:
        if isinstance(node, ast.Assign):
            val_update = expr_is_update(node.value)
            root = _root_name(node.value)
            for t in node.targets:
                names = [t] if isinstance(t, ast.Name) else \
                    [e for e in getattr(t, "elts", [])
                     if isinstance(e, ast.Name)]
                for n in names:
                    if val_update:
                        updated.add(n.id)
                        tainted.add(n.id)
                    elif root in tainted:
                        tainted.add(n.id)
                # ``pool["k"] = pool["k"].at[...].set(...)``: subscript/
                # attribute store into a tainted container.
                if not isinstance(t, ast.Name):
                    troot = _root_name(t)
                    if val_update and troot in tainted:
                        updated.add(troot)
            if val_update and update_line is None:
                update_line = node.lineno
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if expr_is_update(sub):
                    return getattr(sub, "lineno", node.lineno)
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in updated:
                    return update_line or getattr(node, "lineno",
                                                  fn.lineno)
    # Lambda: body already handled via synthetic Return above.
    return None


class _MemVisitor(ast.NodeVisitor):
    """Module walk collecting HVD300/HVD301 source findings."""

    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self.fndefs: Dict[str, ast.AST] = {}

    def run(self, tree: ast.Module) -> List[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fndefs.setdefault(node.name, node)
        # Attribute-bound donated callables are tracked MODULE-wide
        # (``self._fn = jax.jit(step, donate_argnums=...)`` in __init__,
        # called from another method — the engine's copy_block shape);
        # Name bindings stay function-scoped.
        attr_donated: Dict[Tuple[str, str], Set[int]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and _is_jit_call(node.value):
                idxs = _donated_indices(node.value)
                if not idxs:
                    continue
                for t in node.targets:
                    key = _target_key(t)
                    if key is not None and key[0] == "a":
                        attr_donated[key] = idxs
        for node in ast.walk(tree):
            if _is_jit_call(node):
                self._check_hvd300(node)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_hvd301(node, attr_donated)
        # A call inside a nested def is walked both from the outer and
        # the inner FunctionDef — dedupe by site.
        seen: Set[Tuple[str, int, int, str]] = set()
        uniq: List[Finding] = []
        for f in self.findings:
            key = (f.rule, f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                uniq.append(f)
        return uniq

    # -- HVD300: donatable-but-undonated ------------------------------------

    def _check_hvd300(self, call: ast.Call) -> None:
        if _donated_indices(call) is not None:
            return  # donation considered at this jit site
        if not call.args:
            return
        target = call.args[0]
        fn = None
        if isinstance(target, ast.Lambda):
            fn = target
        elif isinstance(target, ast.Name):
            fn = self.fndefs.get(target.id)
        if fn is None:
            return
        line = _fn_updates_and_returns_param(fn)
        if line is None:
            return
        fname = getattr(fn, "name", "<lambda>")
        self.findings.append(Finding(
            rule="HVD300", path=self.path, line=call.lineno,
            col=call.col_offset + 1, source="mem",
            message=f"jit of '{fname}' has no donate_argnums but the "
                    f"function functionally updates a parameter "
                    f"(.at[...] at line {line}) and returns the update — "
                    f"without donation XLA holds both the old and new "
                    f"buffer live"))

    # -- HVD301: donated-then-used ------------------------------------------

    def _check_hvd301(self, fn: ast.AST,
                      attr_donated: Optional[Dict[Tuple[str, str],
                                                  Set[int]]] = None
                      ) -> None:
        donated_callables: Dict[Tuple[str, str], Set[int]] = \
            dict(attr_donated or {})
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not _is_jit_call(node.value):
                continue
            idxs = _donated_indices(node.value)
            if not idxs:
                continue
            for t in node.targets:
                key = _target_key(t)
                if key is not None:
                    donated_callables[key] = idxs

        assigns = [n for n in ast.walk(fn) if isinstance(n, ast.Assign)]
        loads_by_key: Dict[Tuple[str, str], List[ast.AST]] = {}
        stores_by_key: Dict[Tuple[str, str], List[int]] = {}
        for node in ast.walk(fn):
            ctx = getattr(node, "ctx", None)
            key = _target_key(node)
            if key is None:
                continue
            if isinstance(ctx, ast.Load):
                # An Attribute load that is itself the base of a tracked
                # self.attr key shows as both Name load 'self' and the
                # Attribute — only the composite key matters here.
                loads_by_key.setdefault(key, []).append(node)
            elif isinstance(ctx, (ast.Store, ast.Del)):
                stores_by_key.setdefault(key, []).append(node.lineno)

        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            idxs: Optional[Set[int]] = None
            fkey = _target_key(call.func)
            if fkey is not None and fkey in donated_callables:
                idxs = donated_callables[fkey]
            elif _is_jit_call(call.func):
                idxs = _donated_indices(call.func) or None
            if not idxs:
                continue
            enclosing = next(
                (a for a in assigns
                 if any(n is call for n in ast.walk(a.value))), None)
            rebound: Set[Tuple[str, str]] = set()
            if enclosing is not None:
                for t in enclosing.targets:
                    for n in ([t] + list(getattr(t, "elts", []))):
                        k = _target_key(n)
                        if k is not None:
                            rebound.add(k)
            for i in sorted(idxs):
                if i >= len(call.args):
                    continue
                akey = _target_key(call.args[i])
                if akey is None or akey in rebound:
                    continue
                later_stores = [ln for ln in stores_by_key.get(akey, [])
                                if ln > call.lineno]
                horizon = min(later_stores) if later_stores else None
                for use in loads_by_key.get(akey, []):
                    if use.lineno <= call.lineno:
                        continue
                    if horizon is not None and use.lineno >= horizon:
                        continue
                    label = akey[1]
                    self.findings.append(Finding(
                        rule="HVD301", path=self.path, line=use.lineno,
                        col=use.col_offset + 1, source="mem",
                        message=f"'{label}' was donated to the jitted "
                                f"call at line {call.lineno} "
                                f"(donate_argnums position {i}) and is "
                                f"read again here — the buffer is "
                                f"deleted after the call and this read "
                                f"raises at runtime (the PR 4 "
                                f"donated-then-consumed cache hazard)"))
                    break  # one finding per donated arg per call


def analyze_source(source: str, path: str = "<string>",
                   select: Sequence[str] = (),
                   ignore: Sequence[str] = ()) -> List[Finding]:
    """AST --mem pass over one source string (HVD300/HVD301), honoring
    the shared hvdlint pragma + select/ignore contract."""
    from .linter import _parse_pragmas, _suppressed
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError, RecursionError) as e:
        if not rule_selected("HVD000", select, ignore):
            return []
        line = getattr(e, "lineno", 0) or 0
        col = (getattr(e, "offset", 0) or 0)
        return [Finding(rule="HVD000", path=path, line=line,
                        col=max(col, 1), source="mem",
                        message=f"could not parse: {type(e).__name__}: "
                                f"{e}")]
    findings = _MemVisitor(path).run(tree)
    per_line, file_wide = _parse_pragmas(source)
    out: List[Finding] = []
    for f in findings:
        if not rule_selected(f.rule, select, ignore):
            continue
        f.suppressed = _suppressed(f, per_line, file_wide)
        out.append(f)
    return out


def analyze_paths(paths: Iterable[str], select: Sequence[str] = (),
                  ignore: Sequence[str] = ()) -> List[Finding]:
    """AST --mem pass over files/directories (the dogfooding command:
    ``python -m horovod_tpu.analysis --mem horovod_tpu examples``)."""
    from .linter import iter_python_files
    findings: List[Finding] = []
    files: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            if rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=path, line=0, col=1, source="mem",
                    message="path does not exist"))
        else:
            files.append(path)
    for fpath in iter_python_files(files):
        try:
            with open(fpath, "rb") as fh:
                source = fh.read().decode("utf-8", errors="replace")
        except OSError as e:
            if rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=fpath, line=0, col=1, source="mem",
                    message=f"could not read file: {e}"))
            continue
        findings.extend(analyze_source(source, path=fpath, select=select,
                                       ignore=ignore))
    return findings
