"""Trace-level collective-consistency checker + collective census.

The reference framework discovers mismatched collective sequences at
RUNTIME: every rank submits requests, the coordinator's negotiation
phase diffs them, and the job is already wedged when the "Mismatched
allreduce" stall warning prints (controller.cc ComputeResponseList).  On
TPU the whole step program is visible as a jaxpr BEFORE compilation, so
the same contract is checkable statically: walk the (closed) jaxpr —
including ``cond``/``scan``/``while``/``pjit``/``shard_map`` sub-jaxprs —
and

* verify every collective primitive names an axis declared by an
  enclosing mesh/``shard_map`` (HVD101);
* flag ``lax.cond`` branches whose collective *signatures* (ordered
  primitive / axis / shape / dtype sequence) differ — the static
  analogue of the negotiation mismatch (HVD102);
* build a per-step **collective census**: count + estimated payload
  bytes per primitive (``scan`` bodies multiply by trip count; ``while``
  bodies count once and are marked dynamic).  ``timeline.py`` renders
  the census as Chrome-trace counter events and ``bench.py`` attaches it
  to its JSON record under ``HVD_ANALYZE=1``.

A step function that fails to trace is reported as an HVD100 finding —
the checker never raises on user programs, so the ``HVD_ANALYZE=1``
trace-time hook can run mid-training without risk.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, \
    Tuple

import jax

from .findings import Finding

# Axis-name collective primitives across jax versions; unknown names
# simply never match.
COLLECTIVE_PRIMS = {
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pbroadcast",
    "pgather", "psum_invariant",
}


@dataclasses.dataclass
class JaxprReport:
    """Result of one program check: findings + the collective census."""

    label: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    # prim name -> {"count": executions (scan-expanded), "bytes":
    # estimated payload-in bytes across those executions}
    census: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    dynamic_loops: int = 0   # while-loops whose trip count is unknown
    # hvdmem liveness walk of the same program (memplan.MemReport
    # .to_dict(); attached by the HVD_ANALYZE hook): peak_live_bytes,
    # per-primitive allocation breakdown, budget headroom.
    memory: Optional[dict] = None
    # hvdshard sharding/communication walk of the same program
    # (shardplan.CommReport.to_dict(); attached by the HVD_ANALYZE
    # hook): wire bytes, ICI/DCN split, reshard events, budgets.
    comm: Optional[dict] = None

    def ok(self) -> bool:
        return not self.findings

    def total_collectives(self) -> int:
        return sum(c["count"] for c in self.census.values())

    def total_bytes(self) -> int:
        return sum(c["bytes"] for c in self.census.values())

    def to_dict(self) -> dict:
        return {"label": self.label,
                "findings": [f.to_dict() for f in self.findings],
                "census": self.census,
                "dynamic_loops": self.dynamic_loops,
                "memory": self.memory,
                "comm": self.comm}


# -- jaxpr plumbing ---------------------------------------------------------

def _as_jaxpr(obj: Any):
    """Unwrap ClosedJaxpr → Jaxpr; pass Jaxpr through; None otherwise."""
    core = jax.core
    if isinstance(obj, core.ClosedJaxpr):
        return obj.jaxpr
    if isinstance(obj, core.Jaxpr):
        return obj
    return None


def _sub_jaxprs(eqn) -> List[Any]:
    """Every jaxpr hiding in an eqn's params (generic: covers pjit,
    custom_jvp/vjp, remat, closed_call, future primitives)."""
    subs: List[Any] = []
    for val in eqn.params.values():
        for item in (val if isinstance(val, (tuple, list)) else (val,)):
            j = _as_jaxpr(item)
            if j is not None:
                subs.append(j)
    return subs


def _axis_names(params: dict) -> Tuple[str, ...]:
    """String axis names a collective eqn references (ints from vmap's
    positional axes are not mesh axes and are skipped)."""
    raw = params.get("axes", params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def _payload_bytes(eqn) -> int:
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        size = getattr(aval, "size", None)
        dtype = getattr(aval, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * int(dtype.itemsize)
    return total


def _signature(jaxpr) -> Tuple:
    """Ordered collective signature of a (sub)program: the tuple the
    reference's negotiation would have diffed across ranks.  ``scan``
    bodies are expanded by trip count — a psum scanned 2× and one scanned
    5× are DIFFERENT collective sequences at runtime; ``while`` bodies
    (unknown trips) contribute their body signature once."""
    sig: List[Tuple] = []

    def rec(j) -> None:
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                shapes = tuple(
                    (tuple(getattr(v.aval, "shape", ())),
                     str(getattr(v.aval, "dtype", "?")))
                    for v in eqn.invars if getattr(v, "aval", None)
                    is not None)
                sig.append((name, _axis_names(eqn.params), shapes))
            elif name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                sig.extend(_signature(eqn.params.get("jaxpr")) * length)
            else:
                for sub in _sub_jaxprs(eqn):
                    rec(sub)

    j = _as_jaxpr(jaxpr)
    if j is not None:
        rec(j)
    return tuple(sig)


def _fmt_sig(sig: Tuple) -> str:
    if not sig:
        return "(no collectives)"
    return "; ".join(
        f"{name}[{','.join(axes) or '-'}]"
        f"({'+'.join(f'{s}{d}' for s, d in shapes) or '-'})"
        for name, axes, shapes in sig)


# -- the walker -------------------------------------------------------------

class _Walker:
    def __init__(self, report: JaxprReport):
        self.report = report

    def emit(self, rule: str, message: str) -> None:
        self.report.findings.append(Finding(
            rule=rule, path=self.report.label, line=0, col=0,
            message=message, source="jaxpr"))

    def record(self, eqn, mult: int) -> None:
        name = eqn.primitive.name
        entry = self.report.census.setdefault(
            name, {"count": 0, "bytes": 0})
        entry["count"] += mult
        entry["bytes"] += mult * _payload_bytes(eqn)

    def walk(self, jaxpr, declared: Optional[FrozenSet[str]],
             mult: int) -> None:
        j = _as_jaxpr(jaxpr)
        if j is None:
            return
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                self.record(eqn, mult)
                if declared is not None:
                    for axis in _axis_names(eqn.params):
                        if axis not in declared:
                            self.emit(
                                "HVD101",
                                f"collective '{name}' reduces over axis "
                                f"'{axis}' but the enclosing mesh only "
                                f"declares {sorted(declared)}")
                continue
            if name == "cond":
                self._walk_cond(eqn, declared, mult)
            elif name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                self.walk(eqn.params.get("jaxpr"), declared, mult * length)
            elif name in ("while", "while_loop"):
                self.report.dynamic_loops += 1
                self.walk(eqn.params.get("cond_jaxpr"), declared, mult)
                self.walk(eqn.params.get("body_jaxpr"), declared, mult)
            elif name == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = tuple(getattr(mesh, "axis_names", ()) or ())
                inner = (declared or frozenset()) | frozenset(
                    a for a in axes if isinstance(a, str))
                self.walk(eqn.params.get("jaxpr"), inner or None, mult)
            else:
                for sub in _sub_jaxprs(eqn):
                    self.walk(sub, declared, mult)

    def _walk_cond(self, eqn, declared, mult: int) -> None:
        branches = eqn.params.get("branches", ())
        sigs = [_signature(b) for b in branches]
        if sigs and any(s != sigs[0] for s in sigs[1:]):
            rendered = "; vs ".join(
                f"branch {i}: {_fmt_sig(s)}" for i, s in enumerate(sigs))
            self.emit(
                "HVD102",
                f"lax.cond branches disagree on their collective "
                f"signature — {rendered}.  If the predicate diverges "
                f"across ranks this deadlocks exactly like a Horovod "
                f"negotiation mismatch")
        # Census counts every branch's collectives (static upper bound:
        # which branch runs is a runtime property).
        for b in branches:
            self.walk(b, declared, mult)


# -- public API -------------------------------------------------------------

def check_closed_jaxpr(closed_jaxpr,
                       declared_axes: Optional[Sequence[str]] = None,
                       label: str = "<jaxpr>") -> JaxprReport:
    """Check an already-traced program.  ``declared_axes=None`` means "no
    declaration info at this level" — axis checking then activates only
    inside ``shard_map`` regions, whose mesh declares its own axes."""
    report = JaxprReport(label=label)
    declared = frozenset(declared_axes) if declared_axes is not None \
        else None
    _Walker(report).walk(closed_jaxpr, declared, 1)
    return report


def check_step_fn(fn: Callable,
                  args: Sequence[Any] = (),
                  kwargs: Optional[dict] = None,
                  *,
                  axis_env: Optional[Sequence[Tuple[str, int]]] = None,
                  declared_axes: Optional[Sequence[str]] = None,
                  label: Optional[str] = None) -> JaxprReport:
    """Trace ``fn(*args, **kwargs)`` with ``jax.make_jaxpr`` and check it.

    ``axis_env`` binds axis names for tracing un-shard_mapped per-slot
    functions (``[("hvd", 8)]``); a fully wrapped ``shard_map`` step needs
    neither.  ``declared_axes`` is the set the deployment actually
    provides — it defaults to the ``axis_env`` names, so pass it
    explicitly to detect a collective using an axis the mesh won't carry.

    Never raises on the user's program: trace failures come back as an
    HVD100 finding (unbound-axis NameErrors as HVD101), so the
    ``HVD_ANALYZE=1`` hook is safe mid-training.
    """
    name = label or getattr(fn, "__name__", None) or "step"
    kw = kwargs or {}
    try:
        traced = jax.make_jaxpr(
            lambda *a: fn(*a, **kw),
            axis_env=[tuple(e) for e in axis_env] if axis_env else None,
        )(*args)
    except Exception as e:  # loud-but-graceful: report, never crash
        report = JaxprReport(label=name)
        # jax raises NameError("unbound axis name: <axis>") for a
        # collective over an undeclared axis — only that literal message
        # shape is an HVD101.  Any other failure (including an ordinary
        # Python NameError from a typo'd variable, even one *named*
        # something like `axis_scale`) is a generic HVD100.
        if isinstance(e, NameError) and "unbound axis" in str(e).lower():
            report.findings.append(Finding(
                rule="HVD101", path=name, line=0, col=0, source="jaxpr",
                message=f"trace failed on an unbound axis name — a "
                        f"collective references an axis no enclosing "
                        f"mesh/shard_map declares: {e}"))
        else:
            report.findings.append(Finding(
                rule="HVD100", path=name, line=0, col=0, source="jaxpr",
                message=f"step function failed to trace: "
                        f"{type(e).__name__}: {e}"))
        return report
    declared: Optional[Sequence[str]] = declared_axes
    if declared is None and axis_env:
        declared = [a for a, _ in axis_env]
    report = check_closed_jaxpr(traced, declared_axes=declared, label=name)
    # Stash the traced program so downstream analyses (the hvdmem
    # liveness walk in analysis/hook.py) reuse this trace instead of
    # paying a second one; not part of to_dict().
    report._closed_jaxpr = traced
    return report
