"""hvdshard — static sharding & communication-plan analysis (HVD4xx).

The paper's core claim is that Horovod's *runtime* negotiation of
collective consistency becomes a *compile-time* property on XLA/SPMD.
PR 2 made the collectives a program explicitly issues statically
checkable (HVD1xx) and PR 10 did the same for HBM (HVD3xx) — but the
communication GSPMD inserts *silently* is still invisible until a step
is slow on the wrong fabric: a value produced under one sharding and
consumed under another becomes an implicit all-gather; a collective
whose axis spans hosts rides DCN at a fraction of ICI bandwidth.
hvdshard makes the whole communication plan auditable before compile:

Two cooperating halves, mirroring hvdmem's jaxpr/AST split:

* **jaxpr sharding walk** (``measure_closed_jaxpr_comm``): extracts
  per-value shardings from ``pjit``/``sharding_constraint``/``shard_map``
  equations and detects **implicit resharding** — produced under
  sharding A, consumed under sharding B, with estimated bytes moved
  (HVD400; an explicit ``with_sharding_constraint`` is the blessed way
  to reshard and is never flagged).  The same walk builds a
  **communication census**: per-collective payload bytes and wire bytes
  (payload × communicator group size; ``ppermute``/``pshuffle`` move
  their payload once per hop), with every mesh axis classified ICI vs
  DCN (``classify_mesh_axes``: an axis crosses DCN iff moving along it
  changes the device's ``process_index`` — the ``topology.py``
  cross/local split — overridable via ``HVD_COMM_DCN_AXES``).  Rules on
  top of the walk: HVD401 (per-step wire bytes exceed
  ``HVD_COMM_BUDGET_BYTES``; DCN wire bytes exceed the stricter
  ``HVD_COMM_DCN_BUDGET_BYTES`` sub-budget), HVD402 (a large replicated
  operand next to sharded peers that a known mesh axis would shard — the
  comm analogue of HVD300), HVD403 (a collective over an axis the mesh
  doesn't declare, or one flat collective mixing ICI and DCN axes —
  crossing process-set scopes at DCN speed for the whole payload;
  HVD102's negotiation-mismatch concern extended to multi-host process
  sets), HVD404 (a mesh axis of size > 1 that no collective and no
  sharding ever names — dead parallelism wasting chips).

* **AST rules** (``analyze_source`` / ``analyze_paths``, the CLI
  ``--comm`` pass): the source-level shapes — HVD400 (one variable
  annotated with two *different* literal ``PartitionSpec``s via
  ``with_sharding_constraint``/``device_put`` in the same function: GSPMD
  materializes both layouts, one of them via an implicit reshard;
  rebinding the constrained result to a new name is the deliberate-
  resharding idiom and stays clean) and HVD404 (a mesh built from
  literal axes whose sibling axes are exercised by literal specs in the
  same function while one axis never appears — flagged at the mesh
  construction).  Stdlib-only, same pragma/--select/--ignore contract.

Surfacing matches the PR 2/PR 10 censuses: ``HVD_ANALYZE=1`` rides this
walk on the SAME trace the collective and memory censuses use
(analysis/hook.py), the result lands on ``core.analysis_reports()``
(``JaxprReport.comm``), in the active timeline as ``COMM_CENSUS``
counter events, and in bench.py's JSON record under ``comm_census``.
The serve engine folds the comm budget into ``check_replica_plan()`` —
the static go/no-go combining hvdmem's HVD302 pool-vs-budget verdict
with HVD401, exposed on ``kv_stats``/``healthz`` (docs/serving.md): the
admission primitive a tensor/pipeline-sharded replica needs before it
is ever handed traffic.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, rule_selected

#: Reshardings below this are noise (a re-laid-out scalar counter), not
#: a finding; the KV-cache- and activation-sized implicit all-gathers
#: the rule exists for are MBs.  Parameterized per call for tests.
RESHARD_MIN_BYTES = 1 << 20

#: Floor for HVD402: a replicated bias vector next to a sharded batch is
#: the normal data-parallel layout; a replicated multi-MB operand whose
#: leading dim a declared axis divides evenly is a missed sharding.
REPLICATED_MIN_BYTES = 1 << 20


def comm_budget_bytes() -> Optional[int]:
    """Per-step wire-byte budget HVD401 measures against
    (``HVD_COMM_BUDGET_BYTES``); None (unset/malformed) disables the
    check.  Read per call like the sibling hvdmem knobs so a bad value
    degrades to "no budget" instead of breaking import."""
    try:
        env = os.environ.get("HVD_COMM_BUDGET_BYTES", "")
        return int(env) if env else None
    except ValueError:
        return None


def dcn_budget_bytes() -> Optional[int]:
    """The stricter DCN sub-budget (``HVD_COMM_DCN_BUDGET_BYTES``):
    bytes that cross hosts per step.  DCN bandwidth is an order of
    magnitude below ICI, so a plan can fit the total budget and still
    be DCN-bound — this knob catches that separately."""
    try:
        env = os.environ.get("HVD_COMM_DCN_BUDGET_BYTES", "")
        return int(env) if env else None
    except ValueError:
        return None


def dcn_axes_override() -> Tuple[str, ...]:
    """Mesh axes forced to DCN classification (``HVD_COMM_DCN_AXES``,
    comma-separated) — for single-process tests and for analyzing a
    program *for* a multi-host deployment from one host, where every
    local device shares one process_index."""
    raw = os.environ.get("HVD_COMM_DCN_AXES", "")
    return tuple(tok.strip() for tok in raw.split(",") if tok.strip())


def classify_mesh_axes(mesh: Any,
                       dcn_axes: Optional[Sequence[str]] = None
                       ) -> Dict[str, str]:
    """Map each mesh axis name → ``"ici"`` | ``"dcn"``.

    An axis is DCN iff moving along it (holding the other axes fixed)
    changes the device's ``process_index`` — the same host/process split
    ``topology.Topology`` reports as cross vs local, read off the mesh's
    actual device placement.  ``dcn_axes`` (default: the
    ``HVD_COMM_DCN_AXES`` override) forces listed axes to DCN regardless
    of placement.  Unknown/deviceless meshes classify everything ICI —
    the optimistic default matching a single-host run."""
    forced = set(dcn_axes if dcn_axes is not None else dcn_axes_override())
    out: Dict[str, str] = {}
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    devices = getattr(mesh, "devices", None)
    for i, name in enumerate(names):
        if not isinstance(name, str):
            continue
        kind = "ici"
        if name in forced:
            kind = "dcn"
        elif devices is not None:
            try:
                if devices.shape[i] > 1:
                    first = devices.take([0], axis=i)
                    for j in range(1, devices.shape[i]):
                        plane = devices.take([j], axis=i)
                        for a, b in zip(first.flat, plane.flat):
                            if a.process_index != b.process_index:
                                kind = "dcn"
                                break
                        if kind == "dcn":
                            break
            except Exception:
                pass
        out[name] = kind
    return out


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CommReport:
    """Result of one sharding/communication walk."""

    label: str
    # prim name -> {"count": executions (scan-expanded), "bytes": payload
    # bytes in, "wire_bytes": payload x group size, "dcn_bytes": the
    # wire bytes whose axes cross DCN}
    by_primitive: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # axis name -> {"fabric": "ici"|"dcn", "size", "count",
    # "wire_bytes"}: per-axis attribution (a multi-axis collective's
    # wire bytes attribute to each axis it names — an upper bound per
    # axis, exact for single-axis collectives).
    by_axis: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    total_wire_bytes: int = 0
    dcn_wire_bytes: int = 0
    reshard_bytes: int = 0
    reshard_events: List[dict] = dataclasses.field(default_factory=list)
    axes_declared: Dict[str, int] = dataclasses.field(default_factory=dict)
    axes_used: Set[str] = dataclasses.field(default_factory=set)
    dynamic_loops: int = 0
    budget_bytes: Optional[int] = None
    dcn_budget_bytes: Optional[int] = None
    headroom_bytes: Optional[int] = None
    dcn_headroom_bytes: Optional[int] = None
    findings: List[Finding] = dataclasses.field(default_factory=list)

    #: Duck-type compatibility with JaxprReport consumers: a standalone
    #: CommReport carries no collective census and no memory walk.
    @property
    def census(self) -> dict:
        return {}

    @property
    def comm(self) -> dict:
        return self.to_dict()

    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "label": self.label,
            "total_wire_bytes": int(self.total_wire_bytes),
            "dcn_wire_bytes": int(self.dcn_wire_bytes),
            "reshard_bytes": int(self.reshard_bytes),
            "reshard_events": list(self.reshard_events),
            "budget_bytes": self.budget_bytes,
            "dcn_budget_bytes": self.dcn_budget_bytes,
            "headroom_bytes": self.headroom_bytes,
            "dcn_headroom_bytes": self.dcn_headroom_bytes,
            "dynamic_loops": int(self.dynamic_loops),
            "axes_declared": dict(sorted(self.axes_declared.items())),
            "axes_used": sorted(self.axes_used),
            "by_primitive": {k: dict(v)
                             for k, v in sorted(self.by_primitive.items())},
            "by_axis": {k: dict(v)
                        for k, v in sorted(self.by_axis.items())},
        }


# ---------------------------------------------------------------------------
# Jaxpr sharding walk
# ---------------------------------------------------------------------------

def _aval_bytes(aval: Any) -> int:
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        return int(size) * int(dtype.itemsize)
    except Exception:
        return 0


def _spec_key(sharding: Any, ndim: int) -> Optional[Tuple]:
    """Canonical per-dim sharding key of a NamedSharding-style sharding:
    a tuple (length ``ndim``, trailing replicated dims padded with None)
    of per-dim axis-name tuples.  None for UnspecifiedValue / spec-less
    shardings — "no claim", never compared."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    key: List[Optional[Tuple[str, ...]]] = []
    try:
        for entry in spec:
            if entry is None:
                key.append(None)
            elif isinstance(entry, (tuple, list)):
                key.append(tuple(entry))
            else:
                key.append((entry,))
    except TypeError:
        return None
    while len(key) < ndim:
        key.append(None)
    return tuple(key[:ndim])


def _key_axes(key: Optional[Tuple]) -> Set[str]:
    out: Set[str] = set()
    for entry in key or ():
        for axis in entry or ():
            if isinstance(axis, str):
                out.add(axis)
    return out


def _fmt_key(key: Optional[Tuple]) -> str:
    if key is None:
        return "<unspecified>"
    return "P(" + ", ".join(
        "None" if e is None else "+".join(e) for e in key) + ")"


def _axis_strings(obj: Any) -> List[str]:
    """Every axis-name string inside a nested names structure (shard_map
    ``in_names`` dicts ``{dim: (axes,)}``, spec tuples, plain strings)."""
    if isinstance(obj, str):
        return [obj]
    if isinstance(obj, dict):
        return [s for v in obj.values() for s in _axis_strings(v)]
    if isinstance(obj, (tuple, list)):
        return [s for v in obj for s in _axis_strings(v)]
    return []


class _CommWalker:
    """One pass over a (closed) jaxpr accumulating the communication
    census, per-value shardings, and HVD400/402/403 findings.  Mesh
    axes/fabrics accrete as the walk discovers meshes (shard_map params,
    NamedSharding.mesh on pjit shardings) on top of whatever the caller
    declared up front."""

    def __init__(self, report: CommReport, fabrics: Dict[str, str],
                 dcn_axes: Optional[Sequence[str]],
                 reshard_min: int, replicated_min: int):
        import jax
        from .jaxpr_check import COLLECTIVE_PRIMS
        self._var = jax.core.Var
        # shard_map's rewrite mode (check_rep/vma tracking ON) rewrites
        # psum to the psum2 primitive; the repo's compat shim traces with
        # check_rep=False so repo programs keep plain psum, but the census
        # must count both so raw/modern-jax traces measure identically.
        self._collectives = COLLECTIVE_PRIMS | {"psum2"}
        self.report = report
        self.fabrics = fabrics          # axis -> "ici" | "dcn"
        self.dcn_axes = dcn_axes
        self.reshard_min = reshard_min
        self.replicated_min = replicated_min
        self._seen_meshes: Set[int] = set()

    # -- mesh discovery -----------------------------------------------------

    def adopt_mesh(self, mesh: Any) -> None:
        if mesh is None or id(mesh) in self._seen_meshes:
            return
        self._seen_meshes.add(id(mesh))
        try:
            shape = dict(mesh.shape)
        except Exception:
            shape = {}
        for axis, size in shape.items():
            if isinstance(axis, str):
                self.report.axes_declared.setdefault(axis, int(size))
        for axis, kind in classify_mesh_axes(mesh, self.dcn_axes).items():
            # DCN wins: one mesh placing the axis across hosts taints it.
            if self.fabrics.get(axis) != "dcn":
                self.fabrics[axis] = kind

    def _group_size(self, axes: Sequence[str]) -> int:
        g = 1
        for axis in axes:
            g *= max(int(self.report.axes_declared.get(axis, 1)), 1)
        return g

    def _is_dcn(self, axes: Iterable[str]) -> bool:
        return any(self.fabrics.get(a) == "dcn" for a in axes)

    # -- per-eqn handlers ---------------------------------------------------

    def _record_collective(self, eqn, mult: int) -> None:
        from .jaxpr_check import _axis_names, _payload_bytes
        name = eqn.primitive.name
        if name == "psum2":  # rewrite-mode spelling of psum (same wire cost)
            name = "psum"
        axes = _axis_names(eqn.params)
        payload = _payload_bytes(eqn)
        # Wire bytes: payload x communicator group size (the all-gather/
        # reduce upper bound); ppermute/pshuffle rotate the payload one
        # hop, so the group size does not multiply.
        group = 1 if name in ("ppermute", "pshuffle") \
            else self._group_size(axes)
        wire = payload * group
        dcn = self._is_dcn(axes)
        entry = self.report.by_primitive.setdefault(
            name, {"count": 0, "bytes": 0, "wire_bytes": 0, "dcn_bytes": 0})
        entry["count"] += mult
        entry["bytes"] += mult * payload
        entry["wire_bytes"] += mult * wire
        if dcn:
            entry["dcn_bytes"] += mult * wire
        self.report.total_wire_bytes += mult * wire
        if dcn:
            self.report.dcn_wire_bytes += mult * wire
        fabrics_named = set()
        for axis in axes:
            self.report.axes_used.add(axis)
            fabric = self.fabrics.get(axis, "ici")
            fabrics_named.add(fabric)
            ax = self.report.by_axis.setdefault(
                axis, {"fabric": fabric, "size":
                       int(self.report.axes_declared.get(axis, 1)),
                       "count": 0, "wire_bytes": 0})
            ax["fabric"] = fabric
            ax["count"] += mult
            ax["wire_bytes"] += mult * wire
            # HVD403a: the axis is not on any discovered mesh — the
            # static form of reducing over a process set that does not
            # exist in this deployment.
            if self.report.axes_declared and \
                    axis not in self.report.axes_declared:
                self._emit(
                    "HVD403",
                    f"collective '{name}' communicates over axis "
                    f"'{axis}' but the mesh only declares "
                    f"{sorted(self.report.axes_declared)} — no process "
                    f"set carries that axis in this deployment")
        # HVD403b: one flat collective spanning both fabrics — the whole
        # payload crosses process-set scopes at DCN speed instead of the
        # hierarchical ICI-then-DCN decomposition.
        if "ici" in fabrics_named and "dcn" in fabrics_named:
            self._emit(
                "HVD403",
                f"collective '{name}' mixes ICI and DCN axes "
                f"{sorted(axes)} in one flat communicator — the full "
                f"{payload}-byte payload moves at DCN speed; decompose "
                f"hierarchically (ICI axis first, then the DCN axis)")

    def _handle_pjit(self, eqn, known: Dict[Any, Optional[Tuple]],
                     mult: int) -> None:
        in_sh = eqn.params.get("in_shardings") or ()
        out_sh = eqn.params.get("out_shardings") or ()
        sharded_peer_axes: Set[str] = set()
        expected: List[Optional[Tuple]] = []
        for v, s in zip(eqn.invars, in_sh):
            self.adopt_mesh(getattr(s, "mesh", None))
            ndim = len(getattr(getattr(v, "aval", None), "shape", ()))
            key = _spec_key(s, ndim)
            expected.append(key)
            axes = _key_axes(key)
            self.report.axes_used.update(axes)
            sharded_peer_axes.update(axes)
        for v, key in zip(eqn.invars, expected):
            if key is None or not isinstance(v, self._var):
                continue
            prev = known.get(v)
            # HVD400: produced under one sharding, consumed under
            # another — GSPMD inserts the transfer implicitly.
            if prev is not None and prev != key:
                b = _aval_bytes(v.aval)
                if b >= self.reshard_min:
                    moved_axes = _key_axes(prev) | _key_axes(key)
                    self.report.reshard_bytes += mult * b
                    self.report.total_wire_bytes += mult * b
                    if self._is_dcn(moved_axes):
                        self.report.dcn_wire_bytes += mult * b
                    self.report.reshard_events.append({
                        "from": _fmt_key(prev), "to": _fmt_key(key),
                        "bytes": int(b),
                        "shape": list(getattr(v.aval, "shape", ())),
                        "dtype": str(getattr(v.aval, "dtype", "?"))})
                    self._emit(
                        "HVD400",
                        f"implicit resharding: a "
                        f"{str(getattr(v.aval, 'dtype', '?'))}"
                        f"{tuple(getattr(v.aval, 'shape', ()))} value "
                        f"produced under {_fmt_key(prev)} is consumed "
                        f"under {_fmt_key(key)} — GSPMD inserts a "
                        f"~{b}-byte transfer; reshard once explicitly "
                        f"(with_sharding_constraint) or align the specs")
            # HVD402: a large fully-replicated operand riding next to
            # sharded peers — a declared axis that divides its leading
            # dim would shard it instead of mailing every shard a copy.
            if prev is None and key is not None and not _key_axes(key):
                b = _aval_bytes(v.aval)
                shape = tuple(getattr(v.aval, "shape", ()))
                if b >= self.replicated_min and shape:
                    for axis in sorted(sharded_peer_axes):
                        size = self.report.axes_declared.get(axis, 0)
                        if size > 1 and shape[0] % size == 0:
                            self._emit(
                                "HVD402",
                                f"replicated operand "
                                f"{str(getattr(v.aval, 'dtype', '?'))}"
                                f"{shape} ({b} bytes) rides next to "
                                f"peers sharded over '{axis}' (size "
                                f"{size}, which divides dim 0) — "
                                f"sharding it saves "
                                f"{b - b // size} bytes per device")
                            break
        for v, s in zip(eqn.outvars, out_sh):
            self.adopt_mesh(getattr(s, "mesh", None))
            ndim = len(getattr(getattr(v, "aval", None), "shape", ()))
            key = _spec_key(s, ndim)
            if key is not None:
                known[v] = key
                self.report.axes_used.update(_key_axes(key))

    # -- the walk -----------------------------------------------------------

    def _emit(self, rule: str, message: str) -> None:
        self.report.findings.append(Finding(
            rule=rule, path=self.report.label, line=0, col=0,
            message=message, source="comm"))

    def walk(self, jaxpr, mult: int = 1,
             known: Optional[Dict[Any, Optional[Tuple]]] = None) -> None:
        from .jaxpr_check import _as_jaxpr, _sub_jaxprs
        j = _as_jaxpr(jaxpr)
        if j is None:
            return
        known = {} if known is None else known
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in self._collectives:
                self._record_collective(eqn, mult)
            elif name == "pjit":
                self._handle_pjit(eqn, known, mult)
                self.walk(eqn.params.get("jaxpr"), mult)
            elif name == "sharding_constraint":
                # The deliberate-resharding idiom: the author asked for
                # this layout — update the value's sharding, no finding.
                s = eqn.params.get("sharding")
                self.adopt_mesh(getattr(s, "mesh", None))
                for v in eqn.outvars:
                    ndim = len(getattr(getattr(v, "aval", None),
                                       "shape", ()))
                    key = _spec_key(s, ndim)
                    if key is not None:
                        known[v] = key
                        self.report.axes_used.update(_key_axes(key))
            elif name == "shard_map":
                self.adopt_mesh(eqn.params.get("mesh"))
                for names in (eqn.params.get("in_names") or (),
                              eqn.params.get("out_names") or ()):
                    self.report.axes_used.update(
                        a for a in _axis_strings(names)
                        if isinstance(a, str))
                self.walk(eqn.params.get("jaxpr"), mult)
            elif name == "cond":
                for b in eqn.params.get("branches", ()):
                    self.walk(b, mult)
            elif name == "scan":
                length = int(eqn.params.get("length", 1) or 1)
                self.walk(eqn.params.get("jaxpr"), mult * length)
            elif name in ("while", "while_loop"):
                self.report.dynamic_loops += 1
                self.walk(eqn.params.get("cond_jaxpr"), mult)
                self.walk(eqn.params.get("body_jaxpr"), mult)
            else:
                for sub in _sub_jaxprs(eqn):
                    self.walk(sub, mult)


def measure_closed_jaxpr_comm(closed_jaxpr, *,
                              label: str = "<jaxpr>",
                              mesh: Any = None,
                              axis_sizes: Optional[Dict[str, int]] = None,
                              dcn_axes: Optional[Sequence[str]] = None,
                              budget_bytes: Optional[int] = None,
                              dcn_budget: Optional[int] = None,
                              reshard_min_bytes: int = RESHARD_MIN_BYTES,
                              replicated_min_bytes: int =
                              REPLICATED_MIN_BYTES) -> CommReport:
    """Sharding/communication-walk an already-traced program.

    ``mesh`` (the deployment's Mesh, when the caller has it — shard_step
    passes its own) seeds the declared axes and the ICI/DCN fabric map;
    ``axis_sizes`` seeds bare axis extents for axis_env-traced programs
    (DistributedOptimizer's eager path).  The walk itself discovers
    meshes on shard_map eqns and NamedShardings, so both are optional.
    ``budget_bytes``/``dcn_budget`` default to the
    ``HVD_COMM_BUDGET_BYTES``/``HVD_COMM_DCN_BUDGET_BYTES`` knobs; when
    known, HVD401 fires on overshoot."""
    report = CommReport(label=label)
    if axis_sizes:
        for axis, size in axis_sizes.items():
            if isinstance(axis, str):
                report.axes_declared[axis] = int(size)
    walker = _CommWalker(report, fabrics={}, dcn_axes=dcn_axes,
                         reshard_min=reshard_min_bytes,
                         replicated_min=replicated_min_bytes)
    if dcn_axes is None:
        forced = dcn_axes_override()
    else:
        forced = tuple(dcn_axes)
    for axis in forced:
        if axis in report.axes_declared or mesh is None:
            walker.fabrics[axis] = "dcn"
    walker.adopt_mesh(mesh)
    walker.walk(closed_jaxpr, 1)

    # HVD404: declared-but-never-communicated axes — chips reserved for
    # a parallelism dimension the program never exercises.
    for axis, size in sorted(report.axes_declared.items()):
        if size > 1 and axis not in report.axes_used:
            report.findings.append(Finding(
                rule="HVD404", path=label, line=0, col=0, source="comm",
                message=f"mesh axis '{axis}' (size {size}) is never "
                        f"named by a collective or a sharding spec — "
                        f"dead parallelism: {size}x the chips for 1x "
                        f"the work; drop the axis or shard over it"))

    budget = budget_bytes if budget_bytes is not None else \
        comm_budget_bytes()
    report.budget_bytes = budget
    if budget is not None:
        report.headroom_bytes = int(budget) - int(report.total_wire_bytes)
        if report.headroom_bytes < 0:
            report.findings.append(Finding(
                rule="HVD401", path=label, line=0, col=0, source="comm",
                message=f"estimated per-step wire bytes "
                        f"{report.total_wire_bytes} exceed the comm "
                        f"budget {budget} bytes "
                        f"(HVD_COMM_BUDGET_BYTES) by "
                        f"{-report.headroom_bytes} bytes"))
    dbudget = dcn_budget if dcn_budget is not None else dcn_budget_bytes()
    report.dcn_budget_bytes = dbudget
    if dbudget is not None:
        report.dcn_headroom_bytes = \
            int(dbudget) - int(report.dcn_wire_bytes)
        if report.dcn_headroom_bytes < 0:
            report.findings.append(Finding(
                rule="HVD401", path=label, line=0, col=0, source="comm",
                message=f"estimated per-step DCN wire bytes "
                        f"{report.dcn_wire_bytes} exceed the DCN "
                        f"sub-budget {dbudget} bytes "
                        f"(HVD_COMM_DCN_BUDGET_BYTES) by "
                        f"{-report.dcn_headroom_bytes} bytes — the "
                        f"cross-host fabric is the slow one"))
    return report


def measure_step_fn_comm(fn, args: Sequence[Any] = (),
                         kwargs: Optional[dict] = None, *,
                         label: Optional[str] = None,
                         axis_env: Optional[Sequence[Tuple[str, int]]] =
                         None,
                         **measure_kwargs) -> CommReport:
    """Trace ``fn(*args, **kwargs)`` and comm-walk it.  Trace failures
    come back as an empty report (the jaxpr checker owns trace-failure
    reporting, HVD100)."""
    import jax
    name = label or getattr(fn, "__name__", None) or "step"
    kw = kwargs or {}
    try:
        traced = jax.make_jaxpr(
            lambda *a: fn(*a, **kw),
            axis_env=[tuple(e) for e in axis_env] if axis_env else None,
        )(*args)
    except Exception:
        return CommReport(label=name)
    sizes = dict(axis_env) if axis_env else None
    return measure_closed_jaxpr_comm(traced, label=name,
                                     axis_sizes=sizes, **measure_kwargs)


# ---------------------------------------------------------------------------
# Replica-plan go/no-go (the serve layer's admission primitive)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PlanVerdict:
    """One static go/no-go for a replica plan: hvdmem's pool-vs-budget
    verdict (HVD302) combined with the comm budget (HVD401)."""

    label: str
    go: bool
    mem: dict
    comm: dict
    findings: List[Finding] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"label": self.label, "go": self.go,
                "mem": self.mem, "comm": self.comm,
                "findings": [f.to_dict() for f in self.findings]}


def check_replica_plan(label: str, *,
                       pool_bytes: int = 0,
                       weight_bytes: int = 0,
                       step_comm_bytes: int = 0,
                       step_dcn_bytes: int = 0,
                       mem_budget_bytes: Optional[int] = None,
                       comm_budget: Optional[int] = None,
                       dcn_budget: Optional[int] = None) -> PlanVerdict:
    """Static admission check for one replica plan, BEFORE any traffic:
    does the KV pool + weights fit HBM (hvdmem HVD302), and does the
    per-step decode communication fit the budgets (HVD401, with the
    stricter DCN sub-budget)?  ``go`` is False iff any check fails.

    A data-parallel replica passes trivially (its serve programs census
    zero collectives — the ROADMAP-5 invariant); a tensor/pipeline-
    sharded replica supplies its measured ``CommReport`` bytes.  The
    engine runs this at construction and exposes the verdict on
    ``kv_stats``/``healthz`` (docs/serving.md)."""
    from .memplan import check_pool_budget
    mem = check_pool_budget(label, pool_bytes, weight_bytes,
                            budget=mem_budget_bytes)
    comm = CommReport(label=label,
                      total_wire_bytes=int(step_comm_bytes),
                      dcn_wire_bytes=int(step_dcn_bytes))
    budget = comm_budget if comm_budget is not None else comm_budget_bytes()
    comm.budget_bytes = budget
    if budget is not None:
        comm.headroom_bytes = int(budget) - comm.total_wire_bytes
        if comm.headroom_bytes < 0:
            comm.findings.append(Finding(
                rule="HVD401", path=label, line=0, col=0, source="comm",
                message=f"replica plan's per-step wire bytes "
                        f"{comm.total_wire_bytes} exceed the comm "
                        f"budget {budget} bytes (HVD_COMM_BUDGET_BYTES) "
                        f"by {-comm.headroom_bytes} bytes"))
    dbudget = dcn_budget if dcn_budget is not None else dcn_budget_bytes()
    comm.dcn_budget_bytes = dbudget
    if dbudget is not None:
        comm.dcn_headroom_bytes = int(dbudget) - comm.dcn_wire_bytes
        if comm.dcn_headroom_bytes < 0:
            comm.findings.append(Finding(
                rule="HVD401", path=label, line=0, col=0, source="comm",
                message=f"replica plan's per-step DCN bytes "
                        f"{comm.dcn_wire_bytes} exceed the DCN "
                        f"sub-budget {dbudget} bytes "
                        f"(HVD_COMM_DCN_BUDGET_BYTES) by "
                        f"{-comm.dcn_headroom_bytes} bytes"))
    findings = list(mem.findings) + list(comm.findings)
    return PlanVerdict(label=label, go=not findings,
                       mem=mem.to_dict(), comm=comm.to_dict(),
                       findings=findings)


def publish_report(report: CommReport) -> None:
    """Log findings, append to ``core.analysis_reports()``, and chart
    the comm census on the active timeline — the exact surfacing the
    collective/memory censuses use.  Never raises."""
    from ..utils import get_logger
    log = get_logger()
    for f in report.findings:
        log.warning("hvdshard: %s", f.format())
    try:
        from .. import core as _core
        _core._state.analysis_reports.append(report)
        tl = _core._state.timeline
        if tl is not None:
            tl.comm_census(report.label, report.to_dict())
    except Exception as e:  # pragma: no cover - publication is best-effort
        log.warning("hvdshard: could not publish report: %s", e)


def publish_verdict(verdict: PlanVerdict) -> None:
    """Surface a failed (or any) replica-plan verdict the same way a
    trace-time report is surfaced: findings logged as warnings, the
    verdict appended to ``core.analysis_reports()``.  Never raises."""
    from ..utils import get_logger
    log = get_logger()
    for f in verdict.findings:
        log.warning("hvdshard: %s", f.format())
    try:
        from .. import core as _core
        _core._state.analysis_reports.append(verdict)
    except Exception as e:  # pragma: no cover - publication is best-effort
        log.warning("hvdshard: could not publish verdict: %s", e)


# ---------------------------------------------------------------------------
# AST half (the CLI --comm pass): HVD400 / HVD404 source shapes
# ---------------------------------------------------------------------------

_CONSTRAIN_FNS = {"with_sharding_constraint", "device_put"}
_MESH_CTORS = {"Mesh", "make_mesh", "make_hierarchical_mesh"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _literal_pspec(node: ast.AST) -> Optional[Tuple]:
    """The canonical key of a literal ``P(...)``/``PartitionSpec(...)``
    call found anywhere inside ``node`` (e.g. bare, or wrapped in
    ``NamedSharding(mesh, P(...))``).  None when there is no literal
    spec — a computed spec makes no static claim."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if _call_name(sub) not in ("P", "PartitionSpec"):
            continue
        key: List[Optional[Tuple[str, ...]]] = []
        for arg in sub.args:
            if isinstance(arg, ast.Constant) and arg.value is None:
                key.append(None)
            elif isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                key.append((arg.value,))
            elif isinstance(arg, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and
                    isinstance(e.value, str) for e in arg.elts):
                key.append(tuple(e.value for e in arg.elts))
            else:
                return None  # partially dynamic: no static claim
        return tuple(key)
    return None


def _mesh_literal_axes(call: ast.Call) -> Optional[List[str]]:
    """Literal axis names of a mesh constructor call: the dict keys of
    ``make_mesh({"x": ..})`` or the string tuple of
    ``Mesh(devs, ("x", "y"))`` / ``axis_names=(...)``.  None when the
    axes are not statically visible."""
    candidates: List[ast.AST] = list(call.args)
    for kw in call.keywords:
        if kw.arg in ("axes", "axis_names", "shape"):
            candidates.insert(0, kw.value)
    for arg in candidates:
        if isinstance(arg, ast.Dict) and arg.keys and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in arg.keys if k is not None):
            return [k.value for k in arg.keys if k is not None]
        if isinstance(arg, (ast.Tuple, ast.List)) and arg.elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in arg.elts):
            return [e.value for e in arg.elts]
    return None


def _iter_functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _CommVisitor:
    """Module walk collecting the HVD400/HVD404 source findings."""

    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []

    def run(self, tree: ast.Module) -> List[Finding]:
        for fn in _iter_functions(tree):
            self._check_hvd400(fn)
            self._check_hvd404(fn)
        seen: Set[Tuple] = set()
        uniq: List[Finding] = []
        for f in sorted(self.findings,
                        key=lambda f: (f.line, f.col, f.rule)):
            key = (f.rule, f.line, f.col)
            if key not in seen:
                seen.add(key)
                uniq.append(f)
        return uniq

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message, source="comm"))

    # -- HVD400: one value annotated with two different literal specs --------

    def _check_hvd400(self, fn: ast.AST) -> None:
        """``with_sharding_constraint(x, P("a"))`` and later
        ``with_sharding_constraint(x, P("b"))`` on the SAME name in one
        function: GSPMD materializes ``x`` under both layouts — one of
        them is an implicit reshard.  Rebinding the constrained result
        (``y = with_sharding_constraint(x, ...)``, then using ``y``) is
        the deliberate-resharding idiom and stays clean."""
        first: Dict[str, Tuple[Tuple, ast.Call]] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node) not in _CONSTRAIN_FNS:
                continue
            target = node.args[0]
            if not isinstance(target, ast.Name):
                continue
            if len(node.args) < 2 and not node.keywords:
                continue
            spec_src = node.args[1] if len(node.args) > 1 else node
            key = _literal_pspec(spec_src)
            if key is None:
                continue
            prev = first.get(target.id)
            if prev is None:
                first[target.id] = (key, node)
            elif prev[0] != key:
                self._emit(
                    "HVD400", node,
                    f"'{target.id}' is annotated with "
                    f"{_fmt_key(key)} here but with "
                    f"{_fmt_key(prev[0])} at line {prev[1].lineno} — "
                    f"consuming one value under two shardings makes "
                    f"GSPMD materialize both layouts (an implicit "
                    f"reshard); rebind the constrained result to a new "
                    f"name if the second layout is deliberate")

    # -- HVD404: mesh axis never exercised by this function's specs ---------

    def _check_hvd404(self, fn: ast.AST) -> None:
        """A mesh built from literal axes, consumed in the same function
        whose literal specs exercise SOME of those axes but never one of
        them: the dead axis multiplies chips without parallelizing
        anything.  Meshes that escape (returned / stored on self) are
        skipped — their axes may be used by callers."""
        meshes: List[Tuple[str, List[str], ast.Call]] = []
        escaped: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _call_name(node.value) in _MESH_CTORS:
                axes = _mesh_literal_axes(node.value)
                if not axes:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        meshes.append((t.id, axes, node.value))
                    else:
                        escaped.add("")  # stored into an attribute etc.
            elif isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        escaped.add(sub.id)
        if not meshes:
            return
        mesh_lines = {m[2].lineno for m in meshes}
        used: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    _call_name(node) in ("P", "PartitionSpec") and \
                    getattr(node, "lineno", 0) not in mesh_lines:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        used.add(sub.value)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis") and \
                            isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, str):
                        used.add(kw.value.value)
        if not used:
            return  # no literal spec usage at all: no static claim
        for name, axes, call in meshes:
            if name in escaped:
                continue
            dead = [a for a in axes if a not in used]
            if dead and len(dead) < len(axes):
                self._emit(
                    "HVD404", call,
                    f"mesh '{name}' declares axes {axes} but "
                    f"{dead} never appear in any spec or axis_name in "
                    f"this function while {sorted(set(axes) - set(dead))} "
                    f"do — dead parallelism: those chips replicate work")


def analyze_source(source: str, path: str = "<string>",
                   select: Sequence[str] = (),
                   ignore: Sequence[str] = ()) -> List[Finding]:
    """AST --comm pass over one source string (HVD400/HVD404 source
    shapes), honoring the shared hvdlint pragma + select/ignore
    contract."""
    from .linter import _parse_pragmas, _suppressed
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError, RecursionError) as e:
        if not rule_selected("HVD000", select, ignore):
            return []
        line = getattr(e, "lineno", 0) or 0
        col = (getattr(e, "offset", 0) or 0)
        return [Finding(rule="HVD000", path=path, line=line,
                        col=max(col, 1), source="comm",
                        message=f"could not parse: {type(e).__name__}: "
                                f"{e}")]
    findings = _CommVisitor(path).run(tree)
    per_line, file_wide = _parse_pragmas(source)
    out: List[Finding] = []
    for f in findings:
        if not rule_selected(f.rule, select, ignore):
            continue
        f.suppressed = _suppressed(f, per_line, file_wide)
        out.append(f)
    return out


def analyze_paths(paths: Iterable[str], select: Sequence[str] = (),
                  ignore: Sequence[str] = ()) -> List[Finding]:
    """AST --comm pass over files/directories (the dogfooding command:
    ``python -m horovod_tpu.analysis --comm horovod_tpu examples``)."""
    from .linter import iter_python_files
    findings: List[Finding] = []
    files: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            if rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=path, line=0, col=1,
                    source="comm", message="path does not exist"))
        else:
            files.append(path)
    for fpath in iter_python_files(files):
        try:
            with open(fpath, "rb") as fh:
                source = fh.read().decode("utf-8", errors="replace")
        except OSError as e:
            if rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=fpath, line=0, col=1,
                    source="comm",
                    message=f"could not read file: {e}"))
            continue
        findings.extend(analyze_source(source, path=fpath, select=select,
                                       ignore=ignore))
    return findings
