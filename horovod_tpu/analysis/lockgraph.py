"""hvdrace static half: lock-order & thread-lifecycle analysis (HVD20x).

The serve/elastic control plane is a heavily threaded system whose two
worst historical bugs were concurrency bugs found by hand: the
batcher-lock/metrics-lock AB/BA deadlock (PR 3) and the revived-engine
duplicate-loop thread leak (PR 5).  This module reports those classes
statically, in the spirit of FreeBSD's WITNESS lock-order checker and
ThreadSanitizer, adapted to pure-Python control-plane code:

* **HVD200** — lock-order cycle.  Locks are identified by their
  *creation site class* (``DynamicBatcher._lock``), so two instances of
  the same class share an identity, exactly like WITNESS lock classes.
  An edge A→B means "some path acquires B while holding A"; edges are
  collected per function and closed over the same- and known-class call
  graph (``self.method()``, ``self.attr.method()`` where ``attr``'s
  class is statically known, bare in-module calls).  A cycle in the
  global graph is a potential deadlock; the finding prints one witness
  path per direction.
* **HVD201** — blocking call (KV/HTTP transport, subprocess,
  ``time.sleep``, ``Thread.join``, in-module jit-compiled function)
  while holding a lock.
* **HVD202** — callback/user-hook (``on_*`` / ``*_fn`` / ``*_callback``
  / ``*_hook`` attributes or registered-callable containers) invoked
  while holding a lock — the exact shape of the PR 3 ``on_shed`` bug.
* **HVD203** — non-daemon ``threading.Thread`` with no tracked
  ``join()`` on any stop/close path.

Declared orders: ``# hvdrace: order=A<B`` (comment token anywhere in an
analyzed file; lock names as the findings print them) declares that A is
*intended* to be acquired before B.  A declared pair does not silence a
cycle — it re-attributes it: the report points at the acquisition that
INVERTS the declaration, and a single observed B-while-holding-A edge
fires even when the analyzer cannot see the matching A→B path.
Contradictory declarations (both directions) are themselves reported.
Per-line ``# hvdlint: disable=HVD200`` pragmas work as in the linter for
the rare over-approximation false positive.

Like the linter, this module is stdlib-only (ast + tokenize) and never
raises on user input: unparseable files surface as HVD000 findings.
"""

from __future__ import annotations

import ast
import os
import re
import tokenize
import io
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from . import rules as _rules
from .rules import _dotted

# Lock constructors (threading module factories/classes).  Semaphores
# gate counts rather than exclusive regions but still order-deadlock.
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
              "Semaphore": "lock", "BoundedSemaphore": "lock"}

# Method-name tables for HVD201 (blocking while holding a lock).
_BLOCKING_SLEEP = {"sleep"}
_BLOCKING_SUBPROCESS = {"run", "call", "check_output", "check_call",
                        "Popen", "communicate"}
_BLOCKING_HTTP = {"urlopen", "getresponse", "request", "create_connection"}
_HTTPISH_BASES = ("http", "conn", "sock", "client", "session", "url")

_CALLBACK_NAME = re.compile(
    r"(^on_)|(^_on_)|(_cb$)|(_callback$)|(_callbacks$)|(_hook$)|(_hooks$)"
    r"|(_fn$)|(_fns$)|(^callback)|(^hook)")

_STOPPISH = re.compile(
    r"stop|close|shutdown|teardown|finalize|terminate|join|__exit__|__del__",
    re.IGNORECASE)

_ORDER_PRAGMA = re.compile(
    r"#\s*hvdrace:\s*order\s*=\s*([A-Za-z0-9_.:]+)\s*<\s*([A-Za-z0-9_.:]+)")


def _is_kv_request(dotted: str) -> bool:
    """A KV-transport verb through a base that is recognizably a CLIENT
    (narrower than HVD009's any-'kv'-base: ``kv_stats.get(...)`` is a
    dict read and ``self.rendezvous.put(...)`` an in-process server
    write, not round-trips — the dogfood runs' false positives)."""
    parts = dotted.split(".")
    if len(parts) < 2 or parts[-1] not in _rules.KV_TRANSPORT_FNS:
        return False
    return any("client" in p.lower() or p.lower() == "kv"
               for p in parts[:-1])


# ---------------------------------------------------------------------------
# Per-module model
# ---------------------------------------------------------------------------

class _LockInfo:
    """One lock identity: ``Class.attr`` or ``module:NAME``."""

    def __init__(self, label: str, kind: str, path: str, line: int):
        self.label = label
        self.kind = kind  # lock | rlock | condition
        self.path = path
        self.line = line


class _ClassInfo:
    def __init__(self, name: str, node: ast.ClassDef, module: "_ModuleInfo"):
        self.name = name
        self.node = node
        self.module = module
        self.methods: Dict[str, ast.AST] = {}
        self.lock_attrs: Dict[str, _LockInfo] = {}   # attr -> lock
        self.lock_alias: Dict[str, str] = {}         # cond attr -> lock attr
        self.attr_class: Dict[str, str] = {}         # attr -> class name
        self.joined_attrs: Set[str] = set()          # attrs .join()ed

    def lock_for_attr(self, attr: str) -> Optional[_LockInfo]:
        attr = self.lock_alias.get(attr, attr)
        return self.lock_attrs.get(attr)


class _ModuleInfo:
    def __init__(self, tree: ast.Module, path: str, source: str):
        self.tree = tree
        self.path = path
        self.source = source
        self.classes: Dict[str, _ClassInfo] = {}
        self.functions: Dict[str, ast.AST] = {}
        self.module_locks: Dict[str, _LockInfo] = {}  # global name -> lock
        self.declared_orders: List[Tuple[str, str, int]] = []
        # rules._Module gives traced-function marking for the jit arm of
        # HVD201 (same syntactic closure the traced-fn detector uses).
        try:
            self.rules_mod = _rules._Module(tree, path)
        except RecursionError:  # pragma: no cover - pathological nesting
            self.rules_mod = None

    @property
    def label(self) -> str:
        return os.path.splitext(os.path.basename(self.path))[0]


def _lock_ctor(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    last = parts[-1]
    if last not in LOCK_CTORS:
        return None
    # Accept bare (from threading import Lock) and threading.Lock; reject
    # e.g. multiprocessing.Condition?  Same semantics — accept any base.
    return LOCK_CTORS[last]


def _unwrap_value(value: ast.AST) -> ast.AST:
    """Peel ``x or Ctor()`` / ``Ctor() if c else y`` down to the Call arm
    (common default-argument idioms for owned sub-objects)."""
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            if isinstance(v, ast.Call):
                return v
    if isinstance(value, ast.IfExp):
        for v in (value.body, value.orelse):
            if isinstance(v, ast.Call):
                return v
    return value


def _annotation_names(node: ast.AST) -> List[str]:
    """Class names referenced by a parameter annotation, including string
    annotations and Optional[...]/"..." forms."""
    names: List[str] = []
    if node is None:
        return names
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return names
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            names.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            names.append(sub.attr)
    return names


def _index_module(tree: ast.Module, path: str, source: str) -> _ModuleInfo:
    mod = _ModuleInfo(tree, path, source)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            ci = _ClassInfo(node.name, node, mod)
            mod.classes[node.name] = ci
            _index_class(ci)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = node
        elif isinstance(node, ast.Assign):
            value = _unwrap_value(node.value)
            if isinstance(value, ast.Call):
                kind = _lock_ctor(value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mod.module_locks[tgt.id] = _LockInfo(
                                f"{mod.label}:{tgt.id}", kind, path,
                                node.lineno)
    mod.declared_orders = _parse_order_pragmas(source)
    return mod


def _index_class(ci: _ClassInfo) -> None:
    # Class-body assignments (e.g. batcher._Counter.lock) count as lock
    # attrs too; methods indexed for call resolution.
    for node in ci.node.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            ci.methods[node.name] = node
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            kind = _lock_ctor(node.value)
            if kind:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        ci.lock_attrs[tgt.id] = _LockInfo(
                            f"{ci.name}.{tgt.id}", kind,
                            ci.module.path, node.lineno)
    # self.X = ... assignments anywhere in the class body (mostly
    # __init__): locks, condition aliases, attribute classes, threads.
    ann: Dict[str, List[str]] = {}
    init = ci.methods.get("__init__")
    if init is not None:
        args = init.args
        for a in list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs):
            names = _annotation_names(a.annotation)
            if names:
                ann[a.arg] = names
    for node in ast.walk(ci.node):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                root = node.func.value
                if isinstance(root, ast.Attribute) and \
                        isinstance(root.value, ast.Name) and \
                        root.value.id in ("self", "cls"):
                    ci.joined_attrs.add(root.attr)
            continue
        value = _unwrap_value(node.value)
        for tgt in targets:
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls")):
                continue
            attr = tgt.attr
            if isinstance(value, ast.Call):
                dotted = _dotted(value.func)
                last = dotted.split(".")[-1] if dotted else ""
                kind = _lock_ctor(value)
                if kind:
                    if kind == "condition" and value.args and \
                            isinstance(value.args[0], ast.Attribute) and \
                            isinstance(value.args[0].value, ast.Name) and \
                            value.args[0].value.id == "self":
                        # Condition(self._lock): SAME lock identity.
                        ci.lock_alias[attr] = value.args[0].attr
                    elif attr not in ci.lock_attrs:
                        ci.lock_attrs[attr] = _LockInfo(
                            f"{ci.name}.{attr}", kind,
                            ci.module.path, node.lineno)
                elif last and last[0].isupper() and last != "Thread":
                    ci.attr_class.setdefault(attr, last)
            elif isinstance(value, ast.Name):
                # self.X = param — resolvable via annotation only.
                for name in ann.get(value.id, ()):
                    if name and name[0].isupper() and \
                            name not in ("Optional", "None"):
                        ci.attr_class.setdefault(attr, name)
                        break


def _parse_order_pragmas(source: str) -> List[Tuple[str, str, int]]:
    out: List[Tuple[str, str, int]] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError, ValueError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _ORDER_PRAGMA.search(tok.string)
        if m:
            out.append((m.group(1), m.group(2), tok.start[0]))
    return out


# ---------------------------------------------------------------------------
# Per-function lock-region walk
# ---------------------------------------------------------------------------

class _Frame:
    """One acquisition/call site for witness-path printing."""

    def __init__(self, path: str, line: int, fn: str, what: str):
        self.path, self.line, self.fn, self.what = path, line, fn, what

    def format(self) -> str:
        return f"{self.path}:{self.line} ({self.fn}) {self.what}"


class _FnSummary:
    def __init__(self, qualname: str, path: str):
        self.qualname = qualname
        self.path = path
        # Locks this function acquires directly: (lock, line, held_at_entry
        # relative) — ordered edges come from the nesting walk below.
        self.acquires: List[Tuple[_LockInfo, int]] = []
        # (callee key, held locks snapshot, line)
        self.calls: List[Tuple[str, Tuple[_LockInfo, ...], int]] = []
        # Direct lock-order edges: (outer, inner, line)
        self.edges: List[Tuple[_LockInfo, _LockInfo, int]] = []
        # HVD200 self-deadlock candidates handled in the walk directly.


class _Analyzer:
    """Whole-run state: every module, the cross-module class registry, the
    global lock graph, and the findings."""

    def __init__(self):
        self.modules: List[_ModuleInfo] = []
        self.classes: Dict[str, List[_ClassInfo]] = {}
        self.findings: List[Finding] = []
        self.summaries: Dict[str, _FnSummary] = {}
        # lock label -> representative frame of first sighting
        self.lock_sites: Dict[str, _LockInfo] = {}
        # (A label, B label) -> witness path (list of _Frame)
        self.graph: Dict[Tuple[str, str], List[_Frame]] = {}
        self.lock_kinds: Dict[str, str] = {}
        self.declared: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # -- loading -----------------------------------------------------------

    def add_module(self, tree: ast.Module, path: str, source: str) -> None:
        mod = _index_module(tree, path, source)
        self.modules.append(mod)
        for name, ci in mod.classes.items():
            self.classes.setdefault(name, []).append(ci)

    def resolve_class(self, name: str,
                      prefer: Optional[_ModuleInfo] = None) \
            -> Optional[_ClassInfo]:
        cands = self.classes.get(name, [])
        if not cands:
            return None
        if prefer is not None:
            same = [c for c in cands if c.module is prefer]
            if same:
                return same[0]
        return cands[0] if len(cands) == 1 else None

    # -- analysis ----------------------------------------------------------

    def run(self) -> List[Finding]:
        for mod in self.modules:
            for ci in mod.classes.values():
                for mname, fn in ci.methods.items():
                    self._walk_function(mod, ci, fn,
                                        f"{ci.name}.{mname}")
            for fname, fn in mod.functions.items():
                self._walk_function(mod, None, fn, fname)
            self._check_threads(mod)
            for a, b, line in mod.declared_orders:
                key = (a, b)
                if key not in self.declared:
                    self.declared[key] = (mod.path, line)
        self._close_call_graph()
        self._check_cycles()
        self._dedup_sort()
        return self.findings

    def emit(self, rule: str, path: str, line: int, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=path, line=line, col=1, message=message,
            source="race"))

    # -- lock resolution ---------------------------------------------------

    def _resolve_lock(self, mod: _ModuleInfo, ci: Optional[_ClassInfo],
                      expr: ast.AST) -> Optional[_LockInfo]:
        """Lock identity of an expression used in ``with``/acquire():
        ``self._lock`` / ``cls.lock`` / module-level ``NAME`` /
        ``self.attr._lock`` (attr of known class)."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and ci is not None:
                return ci.lock_for_attr(expr.attr)
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id in ("self", "cls") and ci is not None:
                owner = self.resolve_class(
                    ci.attr_class.get(base.attr, ""), prefer=mod)
                if owner is not None:
                    return owner.lock_for_attr(expr.attr)
            if isinstance(base, ast.Name):
                owner = None
                cls = self.resolve_class(base.id, prefer=mod)
                if cls is not None:  # ClassName.lock class attribute
                    owner = cls
                if owner is not None:
                    return owner.lock_for_attr(expr.attr)
        elif isinstance(expr, ast.Name):
            return mod.module_locks.get(expr.id)
        return None

    def _resolve_callee(self, mod: _ModuleInfo, ci: Optional[_ClassInfo],
                        call: ast.Call) -> Optional[str]:
        """Summary key of a statically-resolvable callee, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in mod.functions:
                return f"{mod.path}::{func.id}"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and ci is not None:
            if func.attr in ci.methods:
                return f"{ci.module.path}::{ci.name}.{func.attr}"
            return None
        # self.attr.method() with attr of known class (possibly imported
        # from another analyzed module).
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id in ("self", "cls") and ci is not None:
            owner = self.resolve_class(
                ci.attr_class.get(base.attr, ""), prefer=mod)
            if owner is not None and func.attr in owner.methods:
                return f"{owner.module.path}::{owner.name}.{func.attr}"
        return None

    # -- the function walk -------------------------------------------------

    def _walk_function(self, mod: _ModuleInfo, ci: Optional[_ClassInfo],
                       fn: ast.AST, qualname: str) -> None:
        key = f"{mod.path}::{qualname}"
        summary = _FnSummary(qualname, mod.path)
        self.summaries[key] = summary
        held: List[Tuple[_LockInfo, int]] = []

        def register(lock: _LockInfo, line: int) -> None:
            self.lock_sites.setdefault(lock.label, lock)
            self.lock_kinds.setdefault(lock.label, lock.kind)
            for outer, oline in held:
                if outer.label == lock.label:
                    if lock.kind != "rlock":
                        self.emit(
                            "HVD200", mod.path, line,
                            f"'{lock.label}' re-acquired at line {line} "
                            f"while already held (line {oline}) in "
                            f"{qualname} — a non-reentrant "
                            f"{lock.kind} self-deadlocks here")
                    return
            summary.acquires.append((lock, line))
            for outer, _ in held:
                summary.edges.append((outer, lock, line))

        def handle_call(node: ast.Call) -> None:
            callee = self._resolve_callee(mod, ci, node)
            if callee is not None:
                summary.calls.append(
                    (callee, tuple(l for l, _ in held), node.lineno))
            if held:
                self._check_blocking(mod, ci, node, qualname,
                                     [l for l, _ in held])
                self._check_callback(mod, ci, node, qualname,
                                     [l for l, _ in held])

        def walk(nodes: Iterable[ast.AST]) -> None:
            for node in nodes:
                self._walk_stmt(node, mod, ci, held, register,
                                handle_call, walk)

        body = fn.body if isinstance(fn.body, list) else [fn.body]
        walk(body)

    def _walk_stmt(self, node: ast.AST, mod, ci, held, register,
                   handle_call, walk) -> None:
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            acquired: List[_LockInfo] = []
            for item in node.items:
                lock = self._resolve_lock(mod, ci, item.context_expr)
                # Also descend into the context expressions themselves
                # (calls inside them run before acquisition).
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        handle_call(sub)
                if lock is not None:
                    register(lock, node.lineno)
                    if not any(h.label == lock.label for h, _ in held):
                        held.append((lock, node.lineno))
                        acquired.append(lock)
            walk(node.body)
            for lock in acquired:
                for i in range(len(held) - 1, -1, -1):
                    if held[i][0].label == lock.label:
                        del held[i]
                        break
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes walked separately (methods) or skipped
        # Compound statements recurse through the walker so a `with`
        # nested inside them still registers its acquisition (the
        # generic fallthrough below only scans calls).
        if isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call):
                    handle_call(sub)
            walk(node.body)
            walk(node.orelse)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.iter):
                if isinstance(sub, ast.Call):
                    handle_call(sub)
            walk(node.body)
            walk(node.orelse)
            return
        if isinstance(node, ast.Try):
            walk(node.body)
            for handler in node.handlers:
                walk(handler.body)
            walk(node.orelse)
            walk(node.finalbody)
            return
        if hasattr(ast, "Match") and isinstance(node, ast.Match):
            for sub in ast.walk(node.subject):
                if isinstance(sub, ast.Call):
                    handle_call(sub)
            for case in node.cases:
                walk(case.body)
            return
        # acquire()/release() pairs: flow-insensitive within a statement
        # list — acquire() pushes, release() pops the matching lock.
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in ("acquire", "release"):
                lock = self._resolve_lock(mod, ci, call.func.value)
                if lock is not None:
                    if call.func.attr == "acquire":
                        register(lock, node.lineno)
                        if not any(h.label == lock.label for h, _ in held):
                            held.append((lock, node.lineno))
                    else:
                        for i in range(len(held) - 1, -1, -1):
                            if held[i][0].label == lock.label:
                                del held[i]
                                break
                    return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                handle_call(sub)

    # -- HVD201 / HVD202 ---------------------------------------------------

    def _check_blocking(self, mod: _ModuleInfo, ci, call: ast.Call,
                        qualname: str, held: List[_LockInfo]) -> None:
        dotted = _dotted(call.func)
        if not dotted:
            return
        parts = dotted.split(".")
        last = parts[-1]
        what = None
        if last in _BLOCKING_SLEEP and parts[0] in ("time", "sleep"):
            what = f"'{dotted}' sleeps"
        elif last in _BLOCKING_SUBPROCESS and parts[0] == "subprocess":
            what = f"'{dotted}' runs a subprocess"
        elif _is_kv_request(dotted):
            what = f"KV-transport call '{dotted}' does a network round-trip"
        elif last in _BLOCKING_HTTP and (
                len(parts) == 1 or
                any(b in p.lower() for p in parts[:-1]
                    for b in _HTTPISH_BASES) or parts[0] in
                ("urllib", "requests", "socket")):
            what = f"HTTP/socket call '{dotted}' blocks on the network"
        elif last == "join" and len(parts) >= 2 and (
                "thread" in parts[-2].lower() or parts[-2] in ("t", "th")):
            what = f"'{dotted}()' joins a thread"
        elif isinstance(call.func, ast.Name) and mod.rules_mod is not None:
            for fdef in mod.rules_mod.funcs_by_name.get(call.func.id, ()):
                if fdef in mod.rules_mod.traced:
                    what = (f"'{dotted}' is jit-compiled — first call "
                            f"compiles for seconds")
                    break
        if what is None:
            return
        locks = ", ".join(sorted(l.label for l in held))
        self.emit("HVD201", mod.path, call.lineno,
                  f"{what} while {qualname} holds {locks}; every thread "
                  f"needing that lock stalls for the call's full latency")

    def _check_callback(self, mod: _ModuleInfo, ci, call: ast.Call,
                        qualname: str, held: List[_LockInfo]) -> None:
        func = call.func
        name = None
        if isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id in ("self", "cls"):
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Subscript):
            root = func.value
            if isinstance(root, ast.Attribute):
                name = root.attr
            elif isinstance(root, ast.Name):
                name = root.id
        if name is None or not _CALLBACK_NAME.search(name):
            return
        if (ci is not None and name in ci.methods) or \
                name in mod.functions:
            return  # a real, resolvable callee that happens to match
        locks = ", ".join(sorted(l.label for l in held))
        self.emit("HVD202", mod.path, call.lineno,
                  f"callback '{name}' invoked while {qualname} holds "
                  f"{locks} — the callee is arbitrary code that may take "
                  f"its own lock (the PR 3 on_shed deadlock shape); "
                  f"collect callbacks under the lock, fire them after "
                  f"release")

    # -- interprocedural closure -------------------------------------------

    def _close_call_graph(self) -> None:
        """Transitive may-acquire sets per function, then cross-call
        edges: caller holds H at a call whose callee may acquire M ⇒
        edge H→M (witness path: caller site + callee chain)."""
        acq_cache: Dict[str, Dict[str, List[_Frame]]] = {}

        def acq(key: str, stack: Set[str]) -> Dict[str, List[_Frame]]:
            if key in acq_cache:
                return acq_cache[key]
            if key in stack:
                return {}
            stack.add(key)
            summary = self.summaries.get(key)
            out: Dict[str, List[_Frame]] = {}
            if summary is not None:
                for lock, line in summary.acquires:
                    out.setdefault(lock.label, [_Frame(
                        summary.path, line, summary.qualname,
                        f"acquires {lock.label}")])
                for callee, _held, line in summary.calls:
                    for label, chain in acq(callee, stack).items():
                        if label not in out:
                            out[label] = [_Frame(
                                summary.path, line, summary.qualname,
                                f"calls {callee.split('::')[-1]}")] + chain
            stack.discard(key)
            acq_cache[key] = out
            return out

        for key, summary in self.summaries.items():
            # Direct edges first.
            for outer, inner, line in summary.edges:
                self._add_edge(outer.label, inner.label, [
                    _Frame(summary.path, line, summary.qualname,
                           f"acquires {inner.label} while holding "
                           f"{outer.label}")])
            # Call-mediated edges.
            for callee, held, line in summary.calls:
                if not held:
                    continue
                reachable = acq(callee, set())
                for label, chain in reachable.items():
                    for h in held:
                        if h.label != label:
                            self._add_edge(h.label, label, [
                                _Frame(summary.path, line,
                                       summary.qualname,
                                       f"holding {h.label}, calls "
                                       f"{callee.split('::')[-1]}")
                            ] + chain)
                        elif self.lock_kinds.get(label) != "rlock":
                            self.emit(
                                "HVD200", summary.path, line,
                                f"{summary.qualname} holds '{label}' and "
                                f"calls {callee.split('::')[-1]}, which "
                                f"re-acquires it (path: " +
                                " -> ".join(f.format() for f in chain) +
                                f") — a non-reentrant lock self-deadlocks")

    def _add_edge(self, a: str, b: str, path: List[_Frame]) -> None:
        key = (a, b)
        if key not in self.graph:
            self.graph[key] = path

    # -- cycle detection ---------------------------------------------------

    def _check_cycles(self) -> None:
        # Declared-order inversions: a single observed edge B→A with a
        # declaration A<B is reported even without an observed A→B path
        # (the declaration IS the other witness).
        reported: Set[frozenset] = set()
        for (b, a), path in sorted(self.graph.items()):
            decl = self.declared.get((a, b))
            if decl is None:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            dpath, dline = decl
            self.emit(
                "HVD200", path[0].path, path[0].line,
                f"acquisition order {b} -> {a} inverts the declared "
                f"order '{a} < {b}' ({dpath}:{dline}); witness path: " +
                " -> ".join(f.format() for f in path))
        # Contradictory declarations.
        for (a, b), (dpath, dline) in sorted(self.declared.items()):
            if (b, a) in self.declared and a < b:
                opath, oline = self.declared[(b, a)]
                self.emit(
                    "HVD200", dpath, dline,
                    f"contradictory declared orders: '{a} < {b}' here but "
                    f"'{b} < {a}' at {opath}:{oline}")
        # Observed cycles (2-cycles and longer, via DFS over the edge
        # set); each unordered lock set reported once, with one witness
        # path per direction for the 2-cycle case.
        adj: Dict[str, List[str]] = {}
        for (a, b) in self.graph:
            adj.setdefault(a, []).append(b)
        for (a, b), path_ab in sorted(self.graph.items()):
            if (b, a) in self.graph:
                pair = frozenset((a, b))
                if pair in reported or a > b:
                    continue
                reported.add(pair)
                path_ba = self.graph[(b, a)]
                self.emit(
                    "HVD200", path_ab[0].path, path_ab[0].line,
                    f"lock-order cycle between {a} and {b} — "
                    f"path 1 ({a} then {b}): " +
                    " -> ".join(f.format() for f in path_ab) +
                    f"; path 2 ({b} then {a}): " +
                    " -> ".join(f.format() for f in path_ba) +
                    "; if both paths can run concurrently this deadlocks")
                # A disable pragma on EITHER witness head suppresses (the
                # "violating" direction of a cycle is a judgment call).
                self.findings[-1].alt_sites = [
                    (path_ba[0].path, path_ba[0].line)]
        # Longer cycles: DFS from each node with the 2-cycles removed
        # would over-report; a simple 3-cycle scan covers the practical
        # case without a full enumeration.
        labels = sorted(adj)
        for a in labels:
            for b in adj.get(a, ()):
                if b == a or frozenset((a, b)) in reported:
                    continue
                for c in adj.get(b, ()):
                    if c in (a, b):
                        continue
                    if (c, a) in self.graph:
                        trio = frozenset((a, b, c))
                        if trio in reported:
                            continue
                        if any(frozenset(p) in reported for p in
                               ((a, b), (b, c), (c, a))):
                            continue
                        reported.add(trio)
                        frames = (self.graph[(a, b)] +
                                  self.graph[(b, c)] +
                                  self.graph[(c, a)])
                        self.emit(
                            "HVD200", frames[0].path, frames[0].line,
                            f"lock-order cycle {a} -> {b} -> {c} -> {a}; "
                            f"witness: " +
                            " -> ".join(f.format() for f in frames))

    # -- HVD203: thread lifecycle ------------------------------------------

    def _check_threads(self, mod: _ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if not dotted or dotted.split(".")[-1] != "Thread":
                continue
            parts = dotted.split(".")
            if len(parts) > 1 and parts[-2] not in ("threading", "th"):
                continue
            if self._thread_ok(mod, node):
                continue
            self.emit(
                "HVD203", mod.path, node.lineno,
                "non-daemon Thread with no tracked join() on any "
                "stop/close path — interpreter exit blocks on it, and an "
                "exception between spawn and an in-line join leaks it; "
                "pass daemon=True or join the stored handle from every "
                "stop()/close() path")

    def _thread_ok(self, mod: _ModuleInfo, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "daemon":
                if isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
                return True  # dynamic daemon flag: benefit of the doubt
        # Not daemon: find what the Thread is bound to and whether that
        # binding is ever joined.
        rm = mod.rules_mod
        parent = rm.parents.get(call) if rm is not None else None
        # t.daemon = True after construction?
        target_attr = None
        target_name = None
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in ("self", "cls"):
                    target_attr = tgt.attr
                elif isinstance(tgt, ast.Name):
                    target_name = tgt.id
        elif isinstance(parent, (ast.List, ast.Tuple)):
            gp = rm.parents.get(parent) if rm is not None else None
            if isinstance(gp, ast.Assign):
                for tgt in gp.targets:
                    if isinstance(tgt, ast.Name):
                        target_name = tgt.id
        if target_attr is not None:
            # Joined anywhere in the OWNING class (stop/close paths are
            # the convention; any tracked join counts) — an unrelated
            # class joining its own same-named `_thread` must not
            # suppress this one's leak.
            owner = None
            cur = rm.parents.get(call) if rm is not None else None
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    owner = mod.classes.get(cur.name)
                    break
                cur = rm.parents.get(cur)
            if owner is not None:
                return target_attr in owner.joined_attrs
            return any(target_attr in ci.joined_attrs
                       for ci in mod.classes.values())
        if target_name is not None and rm is not None:
            # Same-function .join( on the name, or `name.daemon = True`.
            fns = rm.enclosing_functions(call)
            scope = fns[0] if fns else mod.tree
            for sub in ast.walk(scope):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "join":
                    root = sub.func.value
                    if isinstance(root, ast.Name) and (
                            root.id == target_name):
                        return True
                    # for t in threads: t.join() over the stored list
                    if isinstance(root, ast.Name):
                        for loop in ast.walk(scope):
                            if isinstance(loop, ast.For) and \
                                    isinstance(loop.target, ast.Name) and \
                                    loop.target.id == root.id and \
                                    isinstance(loop.iter, ast.Name) and \
                                    loop.iter.id == target_name:
                                return True
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                tgt.attr == "daemon" and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == target_name and \
                                isinstance(sub.value, ast.Constant) and \
                                sub.value.value:
                            return True
            return False
        # Fire-and-forget `Thread(...).start()` with no daemon flag.
        return False

    # -- ordering ----------------------------------------------------------

    def _dedup_sort(self) -> None:
        seen, out = set(), []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.rule, f.message)):
            key = (f.rule, f.path, f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        self.findings = out


# ---------------------------------------------------------------------------
# Public API (same shape as linter.lint_paths / lint_source)
# ---------------------------------------------------------------------------

def analyze_sources(sources: Sequence[Tuple[str, str]],
                    select: Sequence[str] = (),
                    ignore: Sequence[str] = ()) -> List[Finding]:
    """Race-analyze a set of ``(source, path)`` pairs as ONE program (the
    lock graph is global: serve's batcher lock and metrics lock live in
    different modules).  Returns suppression-filtered Findings."""
    from .linter import _parse_pragmas, _suppressed, _rule_selected

    analyzer = _Analyzer()
    findings: List[Finding] = []
    pragma_by_path: Dict[str, tuple] = {}
    for source, path in sources:
        try:
            tree = ast.parse(source, filename=path)
        except (SyntaxError, ValueError, RecursionError) as e:
            if _rule_selected("HVD000", select, ignore):
                line = getattr(e, "lineno", 0) or 0
                findings.append(Finding(
                    rule="HVD000", path=path, line=line,
                    col=max(getattr(e, "offset", 0) or 0, 1),
                    message=f"could not parse: {type(e).__name__}: {e}",
                    source="race"))
            continue
        analyzer.add_module(tree, path, source)
        pragma_by_path[path] = _parse_pragmas(source)
    findings.extend(analyzer.run())
    out: List[Finding] = []
    for f in findings:
        if not _rule_selected(f.rule, select, ignore):
            continue
        per_line, file_wide = pragma_by_path.get(f.path, ({}, set()))
        f.suppressed = _suppressed(f, per_line, file_wide)
        if not f.suppressed:
            # Cycle findings carry the other direction's witness head
            # (alt_sites); a pragma there suppresses too.
            for apath, aline in getattr(f, "alt_sites", ()):
                a_per_line, a_file_wide = pragma_by_path.get(
                    apath, ({}, set()))
                ids = a_per_line.get(aline, set()) | a_file_wide
                if "ALL" in ids or f.rule in ids:
                    f.suppressed = True
                    break
        out.append(f)
    return out


def analyze_source(source: str, path: str = "<string>",
                   select: Sequence[str] = (),
                   ignore: Sequence[str] = ()) -> List[Finding]:
    """Single-module convenience (corpus tests)."""
    return analyze_sources([(source, path)], select=select, ignore=ignore)


def analyze_paths(paths: Iterable[str], select: Sequence[str] = (),
                  ignore: Sequence[str] = ()) -> List[Finding]:
    """Race-analyze every .py file under the given files/directories as
    one global lock graph (CLI ``--race`` entry)."""
    from .linter import iter_python_files, _rule_selected

    findings: List[Finding] = []
    files: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            if _rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=path, line=0, col=1,
                    message="path does not exist", source="race"))
        else:
            files.append(path)
    sources: List[Tuple[str, str]] = []
    for fpath in iter_python_files(files):
        try:
            with open(fpath, "rb") as fh:
                sources.append(
                    (fh.read().decode("utf-8", errors="replace"), fpath))
        except OSError as e:
            if _rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=fpath, line=0, col=1,
                    message=f"could not read file: {e}", source="race"))
    findings.extend(analyze_sources(sources, select=select, ignore=ignore))
    return findings
