"""hvdlint — distributed-correctness static analysis for horovod_tpu.

Two cooperating layers (see docs/static_analysis.md):

* **AST linter** (rules.py / linter.py): rules HVD001-HVD008 over source
  files — rank-guarded collectives, exception-swallowed collectives,
  unseeded randomness / host side effects / wall clocks / closed-over
  mutation inside traced functions, undeclared axis literals.  Stdlib
  only; runs anywhere.
* **jaxpr checker** (jaxpr_check.py): traces a step function and walks
  the closed jaxpr (cond/scan/while/shard_map sub-jaxprs included) to
  verify collective/axis consistency (HVD101/HVD102) and to build the
  per-step collective census surfaced by timeline.py and bench.py.

CLI: ``python -m horovod_tpu.analysis <paths>`` (or the ``hvdlint``
console script / ``tools/hvdlint.py`` shim); exit 0 clean, 1 findings,
2 internal error.  Trace-time mode: ``HVD_ANALYZE=1`` (hook.py).
"""

from .findings import ERROR, WARNING, Finding, Rule, RULES, unsuppressed  # noqa: F401
from .linter import lint_file, lint_paths, lint_source, iter_python_files  # noqa: F401
from .jaxpr_check import JaxprReport, check_closed_jaxpr, check_step_fn  # noqa: F401
from .cli import main  # noqa: F401
from . import hook  # noqa: F401
