"""hvdlint — distributed-correctness static analysis for horovod_tpu.

Two cooperating layers (see docs/static_analysis.md):

* **AST linter** (rules.py / linter.py): rules HVD001-HVD008 over source
  files — rank-guarded collectives, exception-swallowed collectives,
  unseeded randomness / host side effects / wall clocks / closed-over
  mutation inside traced functions, undeclared axis literals.  Stdlib
  only; runs anywhere.
* **jaxpr checker** (jaxpr_check.py): traces a step function and walks
  the closed jaxpr (cond/scan/while/shard_map sub-jaxprs included) to
  verify collective/axis consistency (HVD101/HVD102) and to build the
  per-step collective census surfaced by timeline.py and bench.py.
* **hvdrace static half** (lockgraph.py): global lock-acquisition-order
  graph + thread-lifecycle analysis over the same paths — lock-order
  cycles (HVD200), blocking calls under locks (HVD201), callbacks under
  locks (HVD202), unjoined non-daemon threads (HVD203).  CLI: ``--race``.
* **hvdrace runtime half** (witness.py): the ``HVD_SANITIZE=1``
  lock-witness sanitizer — wraps ``threading`` locks, maintains the
  order graph live, records HVD210 (observed inversion) / HVD211
  (timeout-less wait holding a second lock) findings.
* **hvdmem** (memplan.py): static HBM liveness/donation/budget analysis
  — a jaxpr liveness walk (peak-live-bytes estimate + per-primitive
  memory census, HVD300/302/303/304, ridden by the ``HVD_ANALYZE=1``
  hook and the serve engine's pool-budget check) and an AST half
  (``--mem``: HVD300/HVD301 donation hazards at the source level).
* **hvdshard** (shardplan.py): static sharding/communication-plan
  analysis — a jaxpr sharding walk (implicit-resharding detection,
  ICI/DCN comm census, budgets, HVD400-404, ridden by the same
  ``HVD_ANALYZE=1`` hook) plus the serve layer's
  ``check_replica_plan()`` go/no-go, and an AST half (``--comm``:
  HVD400/HVD404 source shapes).

CLI: ``python -m horovod_tpu.analysis <paths>`` (or the ``hvdlint``
console script / ``tools/hvdlint.py`` shim); exit 0 clean, 1 findings,
2 internal error — every pass registered in one table (cli.PASSES).
Trace-time mode: ``HVD_ANALYZE=1`` (hook.py); runtime lock witness:
``HVD_SANITIZE=1`` (witness.py).
"""

from .findings import ERROR, WARNING, Finding, Rule, RULES, \
    rule_selected, unsuppressed  # noqa: F401
from .linter import lint_file, lint_paths, lint_source, iter_python_files  # noqa: F401
from .jaxpr_check import JaxprReport, check_closed_jaxpr, check_step_fn  # noqa: F401
from .lockgraph import (analyze_paths as race_paths,  # noqa: F401
                        analyze_source as race_source,
                        analyze_sources as race_sources)
from .memplan import (MemReport, check_pool_budget,  # noqa: F401
                      device_budget_bytes, measure_closed_jaxpr,
                      measure_step_fn,
                      analyze_paths as mem_paths,
                      analyze_source as mem_source)
from .shardplan import (CommReport, PlanVerdict,  # noqa: F401
                        check_replica_plan, classify_mesh_axes,
                        comm_budget_bytes, dcn_budget_bytes,
                        measure_closed_jaxpr_comm, measure_step_fn_comm,
                        analyze_paths as comm_paths,
                        analyze_source as comm_source)
from .cli import main  # noqa: F401
from . import hook  # noqa: F401
from . import witness  # noqa: F401
