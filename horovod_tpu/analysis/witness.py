"""hvdrace runtime half: the HVD_SANITIZE=1 lock-witness sanitizer.

FreeBSD's WITNESS adapted to Python ``threading``: an instrumented lock
factory (plus a monkey-patch installer that routes ``threading.Lock`` /
``RLock`` / ``Condition`` through it) records per-thread held-lock sets
and maintains the acquisition-order graph LIVE.  Lock identity is the
*construction site* (``serve/batcher.py:170``), so every instance of a
class contributes to one witness class — exactly the static half's
(lockgraph.py) identity, observed instead of inferred.

Findings (structured ``Finding`` objects, rule IDs in findings.py):

* **HVD210** — order inversion: lock B acquired while holding A after an
  earlier A-while-holding-B acquisition anywhere in the process.  The
  finding carries both acquisition sites and thread names.
* **HVD211** — ``Condition.wait()`` / ``Event.wait()`` with **no
  timeout** while holding a second lock: the wait releases only its own
  lock; the other one is held until a wakeup that may never come.

The sanitizer NEVER raises into the instrumented program by default —
findings are recorded (``findings()``), published to
``core.analysis_reports()`` (as a ``WitnessReport``) and emitted as
``WITNESS/<rule>`` Timeline instants like the faultline firings.  Set
``HVD_RACE_RAISE=1`` to raise ``LockOrderViolation`` at the violating
acquisition instead (debugging).  Overhead is a few dict operations per
acquisition — cheap enough to run the whole tier-1 suite under
``HVD_SANITIZE=1`` (tests/conftest.py installs it when the env is set).

Usage::

    from horovod_tpu.analysis import witness
    witness.install()            # or maybe_install_from_env()
    ...                          # run the threaded system
    assert not witness.findings()
    witness.uninstall()

``install()`` only wraps locks constructed AFTER it runs; install first,
construct the fleet second.
"""

from __future__ import annotations

import os
import sys
import threading
import _thread
from typing import Dict, List, Optional, Tuple

from .findings import Finding

# Real constructors, captured at import time so the wrappers and the
# sanitizer's own state never recurse through the patched factories.
_REAL_LOCK = _thread.allocate_lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

# Frames whose construction sites must not name the lock (the wrappers
# themselves, and threading.py internals like Event/Thread bookkeeping).
_SKIP_BASENAMES = ("witness.py", "threading.py")


class LockOrderViolation(RuntimeError):
    """Raised at the violating acquisition when HVD_RACE_RAISE=1."""


class _State:
    def __init__(self):
        self.lock = _REAL_LOCK()           # guards graph/findings
        self.local = threading.local()     # .held: List[_Held]
        # (first label, second label) -> (site, thread name) of the first
        # observation of that order.
        self.graph: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self.findings: List[Finding] = []
        self.reported: set = set()         # dedup keys
        self.installed = False
        self.originals: dict = {}
        self.raise_on_violation = False

    def held(self) -> list:
        held = getattr(self.local, "held", None)
        if held is None:
            held = self.local.held = []
        return held


_state = _State()


def enabled() -> bool:
    return os.environ.get("HVD_SANITIZE", "") not in ("", "0", "false",
                                                      "False")


def _raise_enabled() -> bool:
    return os.environ.get("HVD_RACE_RAISE", "") not in ("", "0", "false",
                                                        "False")


def _caller_site() -> str:
    """Construction/acquisition site label: nearest frame outside this
    module and threading.py internals."""
    f = sys._getframe(2)
    while f is not None:
        name = os.path.basename(f.f_code.co_filename)
        if name not in _SKIP_BASENAMES:
            parts = f.f_code.co_filename.replace(os.sep, "/").split("/")
            return "/".join(parts[-2:]) + f":{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


# ---------------------------------------------------------------------------
# Bookkeeping
# ---------------------------------------------------------------------------

class _Held:
    __slots__ = ("label", "oid", "site", "count")

    def __init__(self, label: str, oid: int, site: str):
        self.label = label
        self.oid = oid    # id() of the raw primitive: re-entry detection
        self.site = site
        self.count = 1


def _record_finding(rule: str, site: str, message: str, key) -> None:
    with _state.lock:
        if key in _state.reported:
            return
        _state.reported.add(key)
        path, _, line = site.rpartition(":")
        try:
            lineno = int(line)
        except ValueError:
            path, lineno = site, 0
        finding = Finding(rule=rule, path=path or site, line=lineno, col=1,
                          message=message, source="witness")
        _state.findings.append(finding)
    _publish(finding)
    if _state.raise_on_violation:
        raise LockOrderViolation(finding.format())


def _publish(finding: Finding) -> None:
    """Best-effort surfacing: log, core.analysis_reports(), Timeline
    WITNESS instant.  Never raises into the instrumented program."""
    try:
        from ..utils import get_logger
        get_logger().error("HVD_SANITIZE: %s", finding.format())
    except Exception:
        pass
    try:
        from .. import core as _core
        st = _core._state
        report = next((r for r in st.analysis_reports
                       if isinstance(r, WitnessReport)), None)
        if report is None:
            report = WitnessReport()
            st.analysis_reports.append(report)
        report.findings.append(finding)
        tl = st.timeline
        if tl is not None:
            tl.witness_event(finding.rule, finding.path, finding.line,
                             threading.current_thread().name)
    except Exception:
        pass


class WitnessReport:
    """analysis_reports() entry mirroring JaxprReport's surface."""

    label = "lock-witness"

    def __init__(self):
        self.findings: List[Finding] = []

    def ok(self) -> bool:
        return not self.findings


def _note_acquire(label: str, oid: int) -> None:
    held = _state.held()
    for h in held:
        if h.oid == oid:
            h.count += 1          # re-entrant (RLock): no order edge
            return
    site = _caller_site()
    pending = None
    if held:
        tname = threading.current_thread().name
        # Collect under the state lock, report after releasing it
        # (_record_finding re-takes it; the state lock is a plain,
        # non-reentrant raw lock).
        with _state.lock:
            for h in held:
                if h.label == label:
                    # Distinct instances of the same witness class (two
                    # locks from one construction site): no self-edge.
                    continue
                key = (h.label, label)
                if key not in _state.graph:
                    _state.graph[key] = (site, tname)
                inv = _state.graph.get((label, h.label))
                if inv is not None and pending is None:
                    dedup = ("HVD210", frozenset((h.label, label)))
                    if dedup not in _state.reported:
                        inv_site, inv_thread = inv
                        pending = (dedup, site, (
                            f"lock-order inversion: '{label}' acquired at "
                            f"{site} (thread {tname}) while holding "
                            f"'{h.label}' (acquired {h.site}), but the "
                            f"opposite order '{h.label}'-after-'{label}' "
                            f"was witnessed at {inv_site} (thread "
                            f"{inv_thread}) — an HVD200 AB/BA deadlock "
                            f"observed live"))
    held.append(_Held(label, oid, site))
    if pending is not None:
        _record_finding("HVD210", pending[1], pending[2], pending[0])


def _note_release(oid: int) -> None:
    held = _state.held()
    for i in range(len(held) - 1, -1, -1):
        if held[i].oid == oid:
            held[i].count -= 1
            if held[i].count <= 0:
                del held[i]
            return


# Thread-lifecycle internals whose timeout-less waits are benign by
# construction (Thread.start's _started.wait is always promptly set by
# the child; join waits are the caller's explicit choice surfaced by
# HVD201 statically).  User-level Event.wait goes through threading.py's
# "wait" frame only, which is NOT in this set — it stays checked.
_THREADING_LIFECYCLE_FNS = {"start", "join", "_wait_for_tstate_lock",
                            "_bootstrap", "_bootstrap_inner", "_stop"}


def _wait_is_threading_internal() -> bool:
    f = sys._getframe(2)
    while f is not None:
        name = os.path.basename(f.f_code.co_filename)
        if name == "witness.py":
            f = f.f_back
            continue
        if name != "threading.py":
            return False
        if f.f_code.co_name in _THREADING_LIFECYCLE_FNS:
            return True
        f = f.f_back
    return False


def _check_naked_wait(own_label: Optional[str], timeout) -> None:
    if timeout is not None:
        return
    held = _state.held()
    others = [h for h in held if h.label != own_label]
    if not others:
        return
    if _wait_is_threading_internal():
        return
    site = _caller_site()
    locks = ", ".join(sorted(h.label for h in others))
    _record_finding(
        "HVD211", site,
        f"timeout-less wait at {site} while holding {locks} — the wait "
        f"releases only its own lock; the other lock is held until a "
        f"wakeup that may never come",
        ("HVD211", site))


# ---------------------------------------------------------------------------
# Instrumented lock types
# ---------------------------------------------------------------------------

class SanitizedLock:
    """threading.Lock/RLock stand-in with witness bookkeeping."""

    def __init__(self, raw, label: str):
        self._raw = raw
        self._witness_label = label

    def acquire(self, blocking=True, timeout=-1):
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            try:
                _note_acquire(self._witness_label, id(self._raw))
            except LockOrderViolation:
                # HVD_RACE_RAISE debug mode: the with-statement's
                # __exit__ never runs when __enter__ raises — undo the
                # acquisition or the raw lock is held forever.
                _note_release(id(self._raw))
                self._raw.release()
                raise
        return ok

    def release(self):
        self._raw.release()
        _note_release(id(self._raw))

    def locked(self):
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanitizedLock {self._witness_label} {self._raw!r}>"

    # stdlib Condition integration (it probes these on custom locks).
    def _is_owned(self):
        if hasattr(self._raw, "_is_owned"):
            return self._raw._is_owned()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def _release_save(self):
        _note_release(id(self._raw))
        if hasattr(self._raw, "_release_save"):
            return self._raw._release_save()
        self._raw.release()
        return None

    def _acquire_restore(self, state):
        if hasattr(self._raw, "_acquire_restore"):
            self._raw._acquire_restore(state)
        else:
            self._raw.acquire()
        _note_acquire(self._witness_label, id(self._raw))


class SanitizedRLock(SanitizedLock):
    def locked(self):  # RLocks have no .locked() pre-3.12
        locked = getattr(self._raw, "locked", None)
        return locked() if callable(locked) else False


class SanitizedCondition:
    """threading.Condition stand-in: a real Condition over the underlying
    raw lock, with witness bookkeeping and the HVD211 naked-wait check.
    The condition shares its lock's witness identity (a Condition IS its
    lock plus a wait queue)."""

    def __init__(self, lock=None, label: Optional[str] = None):
        if lock is None:
            lock = SanitizedRLock(_REAL_RLOCK(),
                                  label or _caller_site())
        if isinstance(lock, SanitizedLock):
            self._wrapped = lock
        else:
            self._wrapped = SanitizedLock(lock, label or _caller_site())
        self._witness_label = self._wrapped._witness_label
        # The real Condition drives the RAW lock so its _release_save /
        # _is_owned semantics stay exactly stdlib's.
        self._cond = _REAL_CONDITION(self._wrapped._raw)

    def acquire(self, *args, **kwargs):
        ok = self._wrapped._raw.acquire(*args, **kwargs)
        if ok:
            try:
                _note_acquire(self._witness_label,
                              id(self._wrapped._raw))
            except LockOrderViolation:
                _note_release(id(self._wrapped._raw))
                self._wrapped._raw.release()
                raise
        return ok

    def release(self):
        self._wrapped._raw.release()
        _note_release(id(self._wrapped._raw))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        _check_naked_wait(self._witness_label, timeout)
        _note_release(id(self._wrapped._raw))
        try:
            return self._cond.wait(timeout)
        finally:
            _note_acquire(self._witness_label, id(self._wrapped._raw))

    def wait_for(self, predicate, timeout=None):
        _check_naked_wait(self._witness_label, timeout)
        _note_release(id(self._wrapped._raw))
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _note_acquire(self._witness_label, id(self._wrapped._raw))

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    notifyAll = notify_all

    def __repr__(self):
        return f"<SanitizedCondition {self._witness_label}>"


# ---------------------------------------------------------------------------
# Factories + installer
# ---------------------------------------------------------------------------

def make_lock(label: Optional[str] = None) -> SanitizedLock:
    return SanitizedLock(_REAL_LOCK(), label or _caller_site())


def make_rlock(label: Optional[str] = None) -> SanitizedRLock:
    return SanitizedRLock(_REAL_RLOCK(), label or _caller_site())


def make_condition(lock=None,
                   label: Optional[str] = None) -> SanitizedCondition:
    return SanitizedCondition(lock, label=label)


def install(raise_on_violation: Optional[bool] = None) -> bool:
    """Monkey-patch ``threading.Lock``/``RLock``/``Condition`` so every
    lock constructed from here on is witness-wrapped.  Idempotent;
    returns True when the patch is active after the call."""
    with _state.lock:
        if _state.installed:
            return True
        _state.originals = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "Condition": threading.Condition,
        }
        _state.installed = True
        _state.raise_on_violation = (
            raise_on_violation if raise_on_violation is not None
            else _raise_enabled())
    threading.Lock = make_lock
    threading.RLock = make_rlock
    threading.Condition = make_condition
    return True


def uninstall() -> None:
    """Restore the real factories (already-wrapped locks keep working —
    the wrappers delegate to real primitives)."""
    with _state.lock:
        if not _state.installed:
            return
        originals = _state.originals
        _state.installed = False
        _state.originals = {}
    threading.Lock = originals["Lock"]
    threading.RLock = originals["RLock"]
    threading.Condition = originals["Condition"]


def maybe_install_from_env() -> bool:
    """Install iff ``HVD_SANITIZE`` is set (serve CLI / conftest hook).
    Off by default: one env read, no patching."""
    if not enabled():
        return False
    return install()


def installed() -> bool:
    return _state.installed


def reset() -> None:
    """Clear the witness graph and findings (test isolation).  Held-lock
    state is per-thread and self-clearing; the graph is global."""
    with _state.lock:
        _state.graph.clear()
        _state.findings.clear()
        _state.reported.clear()


def findings() -> List[Finding]:
    with _state.lock:
        return list(_state.findings)


def order_graph() -> Dict[Tuple[str, str], Tuple[str, str]]:
    """Snapshot of the observed acquisition-order graph (diagnostics)."""
    with _state.lock:
        return dict(_state.graph)


def declare_order(first: str, second: str) -> None:
    """Pre-seed the canonical order for a pair of lock sites (the runtime
    analog of the static ``# hvdrace: order=a<b`` pragma): a later
    observation of the opposite order fires HVD210 even if the declared
    direction is never actually witnessed."""
    with _state.lock:
        _state.graph.setdefault((first, second), ("<declared>", "-"))
