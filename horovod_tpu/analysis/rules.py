"""AST rules HVD001-HVD009: distributed-training antipatterns.

The rules encode, as source-level patterns, the failure classes the
reference framework only catches at runtime in the coordinator's
negotiation phase (controller.cc ComputeResponseList "Mismatched
allreduce" stalls) or never catches at all (rank-divergent trace
constants).  ``analyze(tree, path)`` runs every rule over one parsed
module and returns Findings; suppression comments are applied by the
linter (linter.py), not here.

Design notes:

* **Traced-function detection** is syntactic: a function is considered
  traced when it is (a) decorated by a known tracer (``jax.jit``,
  ``pjit``, ``shard_map``, ``pmap``, ``partial(jax.jit, ...)``), (b)
  passed by name or as a lambda into a tracer call (``jit(f)``,
  ``shard_step(f)``, ``lax.scan(body, ...)``), (c) lexically nested
  inside a traced function, or (d) called by name from inside a traced
  function (one-module call-graph closure, so ``shard_step(lambda *a:
  local_step(*a))`` marks ``local_step``).  Cross-module tracing is out
  of scope — the jaxpr checker (jaxpr_check.py) covers what actually got
  traced.
* Rules only ever match syntactically-resolvable names (dotted
  attribute chains ending in a known collective / RNG / clock name);
  aliased imports (``from jax.lax import psum as reduce``) are out of
  scope by design — cheap to evade, but lint is a seatbelt, not a
  sandbox.
* The HVD2xx lock-order / thread-lifecycle rules live in lockgraph.py:
  they need a GLOBAL cross-module lock graph, not the per-module pass
  this file implements (they reuse ``_Module``'s traced-fn closure and
  the name tables here).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from .findings import Finding

# -- name tables ------------------------------------------------------------

# jax.lax collective primitives (axis-name based).
LAX_COLLECTIVES = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pshuffle",
    "all_gather", "all_to_all", "psum_scatter", "pbroadcast",
}

# horovod-API collectives (engine/negotiation based; no axis argument).
HVD_COLLECTIVES = {
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_", "grouped_allreduce_async",
    "grouped_allreduce_async_",
    "allgather", "allgather_async", "grouped_allgather",
    "grouped_allgather_async",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async",
    "grouped_reducescatter", "grouped_reducescatter_async",
    "barrier", "join",
    "broadcast_variables", "broadcast_parameters",
    "broadcast_optimizer_state", "broadcast_object", "allgather_object",
    "sparse_allreduce", "hierarchical_allreduce", "adasum_allreduce",
    "sync_batch_stats",
}

COLLECTIVES = LAX_COLLECTIVES | HVD_COLLECTIVES

# Names that collide with ubiquitous non-collective Python ("".join,
# os.path.join, Thread.join, lax.broadcast the shape op): these only count
# as collectives when called bare or through a recognizably hvd-ish base.
AMBIGUOUS_COLLECTIVES = {"join", "barrier", "broadcast", "broadcast_"}
HVD_BASES = {"hvd", "horovod_tpu", "ops", "_ops", "collective_ops",
             "functions", "eager", "engine"}

# Calls that trace the function passed to them.
TRACER_CALLS = {
    "jit", "pjit", "pmap", "vmap", "xmap", "shard_map", "shard_step",
    "make_jaxpr", "eval_shape", "grad", "value_and_grad", "linearize",
    "vjp", "jvp", "remat", "checkpoint", "scan", "cond", "while_loop",
    "fori_loop", "switch", "associative_scan", "custom_jvp", "custom_vjp",
    "named_call",
}

RANK_NAMES = {"rank", "local_rank", "cross_rank", "process_index",
              "axis_index"}

STDLIB_RANDOM_FNS = {
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "betavariate",
    "expovariate", "lognormvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "triangular", "getrandbits", "randbytes",
}

NP_RANDOM_SEEDABLE = {"RandomState", "default_rng", "Generator",
                      "SeedSequence", "PCG64", "Philox"}
NP_RANDOM_STATE_FNS = {"seed", "get_state", "set_state"}

CLOCK_FNS = {"time", "time_ns", "perf_counter", "perf_counter_ns",
             "monotonic", "monotonic_ns", "process_time",
             "process_time_ns", "clock_gettime"}
DATETIME_FNS = {"now", "utcnow", "today"}

# Closed-over-container mutators.  ``.update`` is deliberately absent: it
# collides with ``optimizer.update(...)`` (optax) in every training step.
MUTATOR_METHODS = {"append", "extend", "insert", "setdefault", "clear",
                   "remove", "pop", "popitem", "add", "write",
                   "writelines", "discard"}

HOST_EFFECT_BARE = {"print", "open", "input", "breakpoint"}
HOST_EFFECT_DOTTED = {"io_callback", "system", "popen", "run", "call",
                      "check_output", "check_call", "Popen"}
HOST_EFFECT_DOTTED_ROOTS = {"os", "subprocess", "io_callback"}

SYNC_METHODS = {"block_until_ready"}
SYNC_DOTTED = {"device_get"}

# KV-transport verbs (runner/http_server.KVStoreClient and the native
# server's API): control-plane calls whose failures must surface — a
# silently-swallowed transport fault is how a preemption watcher dies
# unnoticed (HVD009).  Generic method names (get/put/scan/delete) only
# count when some earlier segment of the call chain looks like a KV
# client ("kv" in the name, or a *client attribute/variable).
KV_TRANSPORT_FNS = {"put", "get", "scan", "put_wait", "put_batch",
                    "delete", "delete_scope", "scan_scope"}


# -- small AST helpers ------------------------------------------------------

def _dotted(func: ast.AST) -> str:
    """'jax.lax.psum' for Attribute chains, 'psum' for bare Names, ''
    when the base is dynamic (call result, subscript)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def _string_consts(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def _root_name(node: ast.AST) -> Optional[str]:
    """Root Name of an attribute/subscript chain ('cache' for
    cache['k'].stats)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _chain_attrs(node: ast.AST) -> Set[str]:
    attrs = set()
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            attrs.add(node.attr)
        node = node.value
    return attrs


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_collective_call(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    last = parts[-1]
    if last not in COLLECTIVES:
        return None
    if last in AMBIGUOUS_COLLECTIVES and len(parts) > 1 and \
            parts[-2] not in HVD_BASES:
        return None
    return last


def _is_rank_source(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        return bool(dotted) and dotted.split(".")[-1] in RANK_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in {"rank", "local_rank", "cross_rank",
                             "process_index"}
    if isinstance(node, ast.Name):
        return node.id in {"rank", "local_rank"}
    return False


# -- the analyzer -----------------------------------------------------------

class _Module:
    """One parsed module plus the derived maps every rule shares."""

    def __init__(self, tree: ast.Module, path: str):
        self.tree = tree
        self.path = path
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.funcs_by_name: Dict[str, List[ast.AST]] = {}
        self.traced: Set[ast.AST] = set()
        self.declared_axes: Set[str] = set()
        self._index()
        self._mark_traced_roots()
        self._propagate_traced()

    # -- indexing ----------------------------------------------------------

    def _index(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs_by_name.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Lambda):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.funcs_by_name.setdefault(
                            tgt.id, []).append(node.value)
            elif isinstance(node, ast.Call):
                self._collect_axis_decls(node)

    def _collect_axis_decls(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        last = dotted.split(".")[-1] if dotted else ""
        if last == "Mesh":
            for arg in call.args[1:2]:
                self.declared_axes.update(_string_consts(arg))
            for kw in call.keywords:
                if kw.arg == "axis_names":
                    self.declared_axes.update(_string_consts(kw.value))
        elif last == "make_mesh":
            for arg in call.args[:1]:
                if isinstance(arg, ast.Dict):
                    for key in arg.keys:
                        if isinstance(key, ast.Constant) and \
                                isinstance(key.value, str):
                            self.declared_axes.add(key.value)
        elif last in {"P", "PartitionSpec"}:
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                self.declared_axes.update(_string_consts(arg))
        elif last in {"pmap", "shard_step", "xmap"}:
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    self.declared_axes.update(_string_consts(kw.value))

    # -- traced-function marking -------------------------------------------

    def _decorator_traces(self, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            dotted = _dotted(dec.func)
            last = dotted.split(".")[-1] if dotted else ""
            if last in TRACER_CALLS:
                return True
            if last == "partial":  # @partial(jax.jit, ...)
                return any(self._decorator_traces(a) for a in dec.args)
            return False
        dotted = _dotted(dec)
        return bool(dotted) and dotted.split(".")[-1] in TRACER_CALLS

    def _mark_traced_roots(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._decorator_traces(d)
                       for d in node.decorator_list):
                    self.traced.add(node)
            elif isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                last = dotted.split(".")[-1] if dotted else ""
                if last not in TRACER_CALLS:
                    continue
                cands = list(node.args) + [kw.value for kw in node.keywords]
                for arg in cands:
                    if isinstance(arg, ast.Lambda):
                        self.traced.add(arg)
                    elif isinstance(arg, ast.Name):
                        for fn in self.funcs_by_name.get(arg.id, ()):
                            self.traced.add(fn)

    def _own_body(self, fn: ast.AST) -> Iterable[ast.AST]:
        """Nodes of fn's body, not descending into nested function bodies
        (those have their own scope; containment handles their tracing)."""
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    yield child  # the def itself, not its body
                else:
                    stack.append(child)

    def _propagate_traced(self) -> None:
        """Close tracing over same-module calls-by-name from traced code."""
        changed = True
        while changed:
            changed = False
            for fn in list(self.traced):
                for node in ast.walk(fn if not isinstance(fn, ast.Lambda)
                                     else fn.body):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Name):
                        for callee in self.funcs_by_name.get(
                                node.func.id, ()):
                            if callee not in self.traced:
                                self.traced.add(callee)
                                changed = True

    # -- context queries ----------------------------------------------------

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        chain = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                chain.append(cur)
            cur = self.parents.get(cur)
        return chain  # innermost first

    def in_traced(self, node: ast.AST) -> bool:
        if isinstance(node, _FUNC_NODES) and node in self.traced:
            return True
        return any(fn in self.traced
                   for fn in self.enclosing_functions(node))

    def fn_locals(self, fn: ast.AST) -> Set[str]:
        names: Set[str] = set()
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        if isinstance(fn, ast.Lambda):
            return names
        for node in self._own_body(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                names.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    names.add((alias.asname or alias.name).split(".")[0])
        return names

    def is_closed_over(self, node: ast.AST, root: str) -> bool:
        """True when ``root`` is not local to any function between ``node``
        and the outermost traced function enclosing it — i.e. mutation of
        it from traced code reaches state that outlives the trace."""
        chain = self.enclosing_functions(node)
        seen_traced = False
        for fn in chain:
            if seen_traced and fn not in self.traced:
                break  # left the traced region: everything further out is
                       # state that survives the trace
            if root in self.fn_locals(fn):
                return False
            if fn in self.traced:
                seen_traced = True
        return True


def analyze(tree: ast.Module, path: str) -> List[Finding]:
    mod = _Module(tree, path)
    findings: List[Finding] = []

    def emit(rule: str, node: ast.AST, message: str) -> None:
        findings.append(Finding(
            rule=rule, path=path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0) + 1,
            message=message))

    _rule_rank_guarded_collective(mod, emit)       # HVD001
    _rule_swallowed_collective(mod, emit)          # HVD002
    _rule_traced_body_calls(mod, emit)             # HVD003/4/5/8 + HVD006
    _rule_closed_over_mutation(mod, emit)          # HVD007
    _rule_swallowed_fault(mod, emit)               # HVD009
    _rule_serve_prng(mod, emit)                    # HVD010 (serve/ only)
    _rule_lock_held_sync(mod, emit)                # HVD011 (serve/ only)

    # Dedup (nested rank-guards can flag one call twice) + stable order.
    seen, out = set(), []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        key = (f.rule, f.line, f.col)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


# -- HVD001: collective under rank-dependent control flow -------------------

def _branch_collectives(branch) -> List[ast.Call]:
    calls = []
    for stmt in branch:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and _is_collective_call(sub):
                calls.append(sub)
    return calls


def _rule_rank_guarded_collective(mod: _Module, emit) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.If):
            continue
        if not any(_is_rank_source(n) for n in ast.walk(node.test)):
            continue
        body_calls = _branch_collectives(node.body)
        else_calls = _branch_collectives(node.orelse)
        # Symmetric branches — both sides issue the same ordered collective
        # sequence (e.g. broadcast-as-root vs broadcast-as-receiver) — mean
        # every rank posts a matching collective: not a deadlock.
        if body_calls and else_calls and \
                [_is_collective_call(c) for c in body_calls] == \
                [_is_collective_call(c) for c in else_calls]:
            continue
        for sub in body_calls + else_calls:
            name = _is_collective_call(sub)
            emit("HVD001", sub,
                 f"collective '{name}' is only reached by ranks "
                 f"satisfying the rank-dependent condition on line "
                 f"{node.lineno}; the other ranks never post it and the "
                 f"job deadlocks")


# -- HVD002: collective inside exception-swallowing try ---------------------

def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    return not any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _rule_swallowed_collective(mod: _Module, emit) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Try):
            continue
        swallowing = [h for h in node.handlers if _handler_swallows(h)]
        if not swallowing:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    name = _is_collective_call(sub)
                    if name:
                        emit("HVD002", sub,
                             f"collective '{name}' runs inside a try whose "
                             f"except (line {swallowing[0].lineno}) swallows "
                             f"exceptions; a rank that raises skips the "
                             f"collective while the others block in it")


# -- HVD003/004/005/006/008: per-call checks --------------------------------

def _unseeded_random(call: ast.Call, dotted: str) -> Optional[str]:
    parts = dotted.split(".")
    last = parts[-1]
    if parts[0] == "random" and len(parts) == 2 and \
            last in STDLIB_RANDOM_FNS:
        return f"stdlib random.{last}() draws from hidden global state"
    if len(parts) >= 3 and parts[-2] == "random" and \
            parts[0] in {"np", "numpy"}:
        if last in NP_RANDOM_SEEDABLE:
            if not call.args and not call.keywords:
                return (f"np.random.{last}() without a seed differs per "
                        f"rank")
            return None
        if last in NP_RANDOM_STATE_FNS:
            return None
        return f"np.random.{last}() draws from the unseeded global RNG"
    return None


def _host_effect(call: ast.Call, dotted: str) -> Optional[str]:
    parts = dotted.split(".")
    last = parts[-1]
    if len(parts) == 1 and last in HOST_EFFECT_BARE:
        return f"'{last}' executes on the host at trace time only"
    if last == "print" and parts[:-1] in (["jax", "debug"], ["debug"]):
        return None  # jax.debug.print is the sanctioned traced print
    if last in HOST_EFFECT_DOTTED and \
            parts[0] in HOST_EFFECT_DOTTED_ROOTS:
        return f"'{dotted}' is a host side effect inside traced code"
    if last == "io_callback":
        return ("'io_callback' adds an ordered host round-trip per step; "
                "ordered callbacks serialize ranks")
    return None


def _clock_call(dotted: str) -> Optional[str]:
    parts = dotted.split(".")
    last = parts[-1]
    if parts[0] == "time" and len(parts) == 2 and last in CLOCK_FNS:
        return f"'{dotted}()' is baked in as a trace-time constant"
    if len(parts) == 1 and last in CLOCK_FNS:
        return f"'{last}()' is baked in as a trace-time constant"
    if last in DATETIME_FNS and "datetime" in parts[:-1]:
        return f"'{dotted}()' is baked in as a trace-time constant"
    return None


def _axis_use(call: ast.Call, last: str) -> List[str]:
    """String axis names this collective call references."""
    exprs: List[ast.AST] = []
    if last in LAX_COLLECTIVES and len(call.args) >= 2:
        exprs.append(call.args[1])
    for kw in call.keywords:
        if kw.arg in {"axis_name", "axis_names"}:
            exprs.append(kw.value)
    names: List[str] = []
    for e in exprs:
        names.extend(_string_consts(e))
    return names


def _rule_traced_body_calls(mod: _Module, emit) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if not dotted:
            continue
        last = dotted.split(".")[-1]
        traced = mod.in_traced(node)

        # HVD006 applies wherever the call sits: axis literals are
        # checkable even outside traced code, but only when the file
        # declares axes at all (otherwise there is nothing to check
        # against).
        if last in LAX_COLLECTIVES and mod.declared_axes:
            for axis in _axis_use(node, last):
                if axis not in mod.declared_axes:
                    emit("HVD006", node,
                         f"collective '{last}' names axis '{axis}' but "
                         f"this file only declares "
                         f"{sorted(mod.declared_axes)}")

        if not traced:
            continue
        msg = _unseeded_random(node, dotted)
        if msg:
            emit("HVD003", node, msg + " inside a traced function")
        msg = _host_effect(node, dotted)
        if msg:
            emit("HVD004", node, msg)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in SYNC_METHODS) or \
                (last in SYNC_DOTTED):
            emit("HVD005", node,
                 f"'{last}' forces a device sync inside the traced step")
        msg = _clock_call(dotted)
        if msg:
            emit("HVD008", node, msg)


# -- HVD009: bare/silent except around collective or KV-transport calls ----

def _is_kv_transport_call(call: ast.Call) -> Optional[str]:
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    if len(parts) < 2 or parts[-1] not in KV_TRANSPORT_FNS:
        return None
    base = [p.lower() for p in parts[:-1]]
    if any("kv" in p or "client" in p for p in base):
        return dotted
    return None


def _silent_handler(handler: ast.ExceptHandler) -> Optional[str]:
    """Why this handler counts as fault-swallowing for HVD009, or None.

    Two shapes (narrower than HVD002's any-non-raising handler):

    * ``except:`` with no re-raise — catches EVERYTHING including
      KeyboardInterrupt/SystemExit, whatever its body does;
    * ``except Exception:`` (or BaseException) whose body is ONLY
      ``pass``/``...``/``continue`` — the fault vanishes without a log
      line, a metric, or a backoff.
    """
    def body_is_silent() -> bool:
        for stmt in handler.body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Constant) and \
                    stmt.value.value is Ellipsis:
                continue
            return False
        return True

    if handler.type is None:
        if not any(isinstance(n, ast.Raise) for n in ast.walk(handler)):
            return "bare 'except:'"
        return None
    names = _string_like_exc_names(handler.type)
    if names & {"Exception", "BaseException"} and body_is_silent():
        return f"'except {sorted(names)[0]}: pass'"
    return None


def _string_like_exc_names(node: ast.AST) -> Set[str]:
    names: Set[str] = set()
    nodes = node.elts if isinstance(node, ast.Tuple) else [node]
    for n in nodes:
        dotted = _dotted(n)
        if dotted:
            names.add(dotted.split(".")[-1])
    return names


def _rule_swallowed_fault(mod: _Module, emit) -> None:
    """HVD009: a collective or KV-transport call inside a try whose
    handler swallows faults SILENTLY (``_silent_handler``).  The
    distributed consequence differs by call class — a swallowed
    collective desynchronizes ranks (HVD002's concern, sharpened here to
    the silent shapes), a swallowed KV-transport fault blinds the
    control plane (a preemption watcher that eats its scan error polls a
    ghost forever) — but the fix is the same: count the error into
    metrics, log it, back off, and keep going, or re-raise."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Try):
            continue
        silent = next((why for why in map(_silent_handler, node.handlers)
                       if why), None)
        if silent is None:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                name = _is_kv_transport_call(sub)
                kind = "KV-transport"
                if name is None:
                    name = _is_collective_call(sub)
                    kind = "collective"
                if name is None:
                    continue
                emit("HVD009", sub,
                     f"{kind} call '{name}' inside a try whose {silent} "
                     f"swallows the fault silently; count it into "
                     f"metrics, back off and retry, or re-raise — a "
                     f"dropped fault here is invisible until the job "
                     f"wedges")


# -- HVD010: reused-or-ambient PRNG in serving code -------------------------

#: jax.random constructors/derivers whose seed provenance HVD010 audits.
PRNG_KEY_FNS = {"PRNGKey", "key", "fold_in"}


def _in_serve_tree(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/serve/" in norm or norm.startswith("serve/")


def _clock_derived(node: ast.AST) -> Optional[str]:
    """The dotted clock/date call feeding ``node``, if any — a PRNG key
    derived from the wall clock differs per replica and per replay."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        dotted = _dotted(sub.func)
        if not dotted:
            continue
        if _clock_call(dotted) is not None:
            return dotted
        last = dotted.split(".")[-1]
        if last in DATETIME_FNS and "datetime" in dotted.split(".")[:-1]:
            return dotted
    return None


def _rule_serve_prng(mod: _Module, emit) -> None:
    """HVD010: serve-aware PRNG provenance (the serving sharpening of
    HVD003's unseeded-randomness concern).  Inside ``serve/``, a
    ``jax.random.PRNGKey``/``key``/``fold_in`` call whose seed derives
    from the wall clock (replay/failover divergence) or is a literal
    constant (every request shares the stream — ambient, rank- and
    request-independent) is flagged; keys must chain from the request
    seed (sampling.seq_key) so batched == single given the same key
    survives requeue, failover, and fork."""
    if not _in_serve_tree(mod.path):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        parts = dotted.split(".") if dotted else []
        last = parts[-1] if parts else ""
        if last not in PRNG_KEY_FNS or not node.args:
            continue
        # Only jax.random-shaped chains: a dotted base must mention
        # ``random`` (jax.random.PRNGKey, _random.fold_in); bare names
        # cover ``from jax.random import ...``.  ``PRNGKey`` is
        # unambiguous under any base.  This keeps dict.key()-style
        # calls out.
        if len(parts) > 1 and last != "PRNGKey" and \
                not any("random" in p for p in parts[:-1]):
            continue
        clock = _clock_derived(node)
        if clock is not None:
            emit("HVD010", node,
                 f"'{last}' seeds serving randomness from the wall clock "
                 f"('{clock}'): a resubmitted/replayed request draws "
                 f"different tokens on every replica")
            continue
        seed_args = (node.args[:1] if last != "fold_in"
                     else node.args[:2])
        if all(isinstance(a, ast.Constant) for a in seed_args):
            emit("HVD010", node,
                 f"'{last}' builds a serving key from constant(s) only — "
                 f"every request (and every rank) draws the same stream; "
                 f"derive it from the request seed (sampling.seq_key)")


# -- HVD011: blocking device sync inside a lock region in serve/ ------------

#: numpy module aliases whose ``asarray`` pulls a device value to host
#: (a blocking sync); ``jnp.asarray`` stays on device and is fine.
_HOST_NP_ALIASES = {"np", "numpy", "onp"}


def _is_lock_ctx(expr: ast.AST) -> bool:
    """``with self._lock:`` / ``with self._kv_lock:`` — an attribute
    whose name mentions "lock" (the repo-wide naming convention the
    hvdrace lockgraph keys on), optionally through ``.acquire()`` or a
    bare Name like ``with lock:``."""
    if isinstance(expr, ast.Call):
        expr = expr.func
        if isinstance(expr, ast.Attribute) and expr.attr == "acquire":
            expr = expr.value
    if isinstance(expr, ast.Attribute):
        return "lock" in expr.attr.lower()
    if isinstance(expr, ast.Name):
        return "lock" in expr.id.lower()
    return False


def _sync_call_kind(node: ast.Call) -> Optional[str]:
    """The blocking-sync shape of a call, if any: ``jax.device_get`` /
    bare ``device_get``, ``<x>.block_until_ready()``, or a host-numpy
    ``asarray`` (which forces the device value across PCIe/host DMA
    before returning)."""
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
        if f.attr == "device_get":
            return "jax.device_get"
        if f.attr == "asarray" and isinstance(f.value, ast.Name) and \
                f.value.id in _HOST_NP_ALIASES:
            return f"{f.value.id}.asarray"
    elif isinstance(f, ast.Name) and f.id == "device_get":
        return "device_get"
    return None


def _rule_lock_held_sync(mod: _Module, emit) -> None:
    """HVD011: a blocking device→host sync inside a ``with self._lock``
    region in serve/ — the static sibling of hvdrace's HVD201 (blocking
    call under a lock): the sync waits for the device to finish the
    whole in-flight program while every other request thread piles up
    on the lock.  Nested function bodies are skipped (they run when
    called, not necessarily under the lock)."""
    if not _in_serve_tree(mod.path):
        return
    lock_withs = [
        node for node in ast.walk(mod.tree)
        if isinstance(node, ast.With) and
        any(_is_lock_ctx(item.context_expr) for item in node.items)]

    def _body_nodes(root_stmts):
        stack = list(root_stmts)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # runs when called, not necessarily under lock
            stack.extend(ast.iter_child_nodes(node))

    seen: Set[int] = set()
    for w in lock_withs:
        for node in _body_nodes(w.body):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            kind = _sync_call_kind(node)
            if kind is None:
                continue
            seen.add(id(node))
            emit("HVD011", node,
                 f"blocking device sync '{kind}' runs while holding "
                 f"the lock taken on line {w.lineno} — the sync waits "
                 f"out the device's whole in-flight program and every "
                 f"other request thread stalls on the lock for that "
                 f"long; snapshot under the lock, release, then fetch")


# -- HVD007: mutation of closed-over state in traced code -------------------

def _rule_closed_over_mutation(mod: _Module, emit) -> None:
    for fn in mod.traced:
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                _check_mutation(mod, node, emit)


def _check_mutation(mod: _Module, node: ast.AST, emit) -> None:
    if isinstance(node, (ast.Global, ast.Nonlocal)):
        emit("HVD007", node,
             f"'{'global' if isinstance(node, ast.Global) else 'nonlocal'} "
             f"{', '.join(node.names)}' rebinds outer state from traced "
             f"code; the write happens once at trace time, not per step")
        return
    targets: List[ast.AST] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in MUTATOR_METHODS:
        root = _root_name(node.func.value)
        if root and "at" not in _chain_attrs(node.func.value) and \
                mod.is_closed_over(node, root):
            emit("HVD007", node,
                 f"'{root}.{node.func.attr}(...)' mutates closed-over "
                 f"'{root}' from traced code (trace-time effect, not a "
                 f"per-step one)")
        return
    for tgt in targets:
        if isinstance(tgt, (ast.Subscript, ast.Attribute)):
            root = _root_name(tgt)
            if root and "at" not in _chain_attrs(tgt) and \
                    mod.is_closed_over(node, root):
                emit("HVD007", tgt,
                     f"assignment into closed-over '{root}' from traced "
                     f"code happens at trace time, not per step, and "
                     f"diverges across independently-tracing ranks")
