"""HVD_ANALYZE=1 trace-time hook: run the jaxpr checker on first compile.

Opt-in via the environment (``HVD_ANALYZE=1``), wired into the two places
a step program first becomes visible:

* ``parallel.shard_step`` — analyzes the full shard_map'd step (model +
  collectives + DistributedOptimizer update) with the first call's
  concrete arguments, once per wrapper instance/arity/generation;
* ``DistributedOptimizer`` — analyzes the gradient-reduction program of
  an *eagerly* driven optimizer (no surrounding shard_step) by tracing
  its update under the framework axis, once per optimizer
  instance/generation;
* the serve engine's prefill/decode builders — registered per compile
  bucket via ``InferenceEngine``'s adapter (engine._maybe_analyze), so
  serve-phase programs get the same census + HVD1xx walk (and must
  census zero collectives — the ROADMAP-5 invariant).

Every analysis also runs the hvdmem liveness walk AND the hvdshard
sharding/communication walk over the SAME traced program (memplan.py /
shardplan.py — no second trace): the memory census attaches as
``JaxprReport.memory`` (HVD300/302/303/304 findings merged,
``Timeline.memory_census`` charts it) and the comm census attaches as
``JaxprReport.comm`` (HVD400-404 findings merged,
``Timeline.comm_census`` charts the wire bytes with their ICI/DCN
split).  All five serve engine build sites ride the same hook, so serve
programs census comm too — and must census ZERO collectives, the
ROADMAP-5 invariant.

Findings are logged as warnings, the report is appended to
``core._state.analysis_reports`` (``core.analysis_reports()``), and the
collective census lands in the active timeline as counter events
(``Timeline.collective_census``) so the trace viewer shows per-step
collective counts/bytes next to the op lifecycle.  The hook NEVER raises
into training code: any analysis failure is logged and swallowed — the
loudly-but-gracefully contract of the HVD100 rule.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
from typing import Any, Optional, Sequence, Tuple

from ..utils import get_logger

_lock = threading.Lock()
_analyzed: set = set()
_generation = 0
_instance_seq = itertools.count(1)  # distinguishes same-named instances


def enabled() -> bool:
    return os.environ.get("HVD_ANALYZE", "") not in ("", "0", "false",
                                                     "False")


def generation() -> int:
    """Monotonic analysis generation, bumped by ``reset()``.  Integration
    sites (shard_step, wrap_optimizer) remember the generation at which
    they analyzed, so an elastic re-init (which calls reset) re-analyzes
    the programs that recompile in the new world."""
    return _generation


def reset() -> None:
    """Start a new analysis generation (new world / test isolation).
    Called by ``core.init`` so every (re)initialized runtime re-analyzes
    its first compile."""
    global _generation
    with _lock:
        _generation += 1
        _analyzed.clear()


def analyze_traceable(fn, args: Sequence[Any],
                      kwargs: Optional[dict] = None, *,
                      label: str,
                      declared_axes: Optional[Sequence[str]] = None,
                      axis_env: Optional[Sequence[Tuple[str, int]]] = None,
                      once: bool = True,
                      donate_argnums: Optional[Sequence[int]] = None,
                      mesh=None):
    """Check ``fn(*args)``; returns the JaxprReport (or None when
    disabled/already done/failed).  ``once=True`` dedupes globally by
    ``label``; callers that own their dedup (shard_step's per-wrapper
    generation tracking, which labels aren't unique enough for) pass
    ``once=False``.  ``donate_argnums`` is the donation the deployment
    compiles with (feeds the hvdmem HVD300 donation check; a jitted
    ``fn`` carries its own ``donated_invars``, so leave it None there).
    ``mesh`` is the deployment Mesh when the caller has one (shard_step
    does) — it seeds the hvdshard walk's axis sizes and ICI/DCN fabric
    classification.  Safe to call on the hot path."""
    if not enabled():
        return None
    if once:
        with _lock:
            if label in _analyzed:
                return None
            _analyzed.add(label)
    log = get_logger()
    try:
        from . import jaxpr_check
        report = jaxpr_check.check_step_fn(
            fn, args, kwargs, axis_env=axis_env,
            declared_axes=declared_axes, label=label)
    except Exception as e:  # never break training over analysis
        log.warning("HVD_ANALYZE: analysis of %s failed: %s: %s",
                    label, type(e).__name__, e)
        return None
    # hvdmem ride-along: liveness-walk the SAME traced program (no
    # second trace) — peak live bytes, per-primitive allocation
    # breakdown, donation/budget/upcast rules HVD300/302/303/304.
    closed = getattr(report, "_closed_jaxpr", None)
    if closed is not None:
        try:
            from . import memplan
            mem = memplan.measure_closed_jaxpr(
                closed, label=label,
                # Per-argument donation expanded to per-leaf invar flags
                # (a donated PYTREE arg donates every one of its leaves).
                donated_invars=memplan.donated_invar_flags(
                    args, donate_argnums))
            report.memory = mem.to_dict()
            report.findings.extend(mem.findings)
        except Exception as e:  # analysis must never break training
            log.warning("HVD_ANALYZE: memory analysis of %s failed: "
                        "%s: %s", label, type(e).__name__, e)
        # hvdshard ride-along: sharding/communication walk of the SAME
        # trace — implicit reshards, ICI/DCN comm census, budget rules
        # HVD400-404.
        try:
            from . import shardplan
            comm = shardplan.measure_closed_jaxpr_comm(
                closed, label=label, mesh=mesh,
                axis_sizes=dict(axis_env) if axis_env else None)
            report.comm = comm.to_dict()
            report.findings.extend(comm.findings)
        except Exception as e:  # analysis must never break training
            log.warning("HVD_ANALYZE: comm analysis of %s failed: "
                        "%s: %s", label, type(e).__name__, e)
    _publish(report, log)
    return report


def _publish(report, log) -> None:
    for f in report.findings:
        log.warning("HVD_ANALYZE: %s", f.format())
    if report.census:
        log.info("HVD_ANALYZE: %s collective census: %s%s",
                 report.label, json.dumps(report.census, sort_keys=True),
                 f" ({report.dynamic_loops} dynamic loop(s) counted once)"
                 if report.dynamic_loops else "")
    try:
        from .. import core as _core
        st = _core._state
        st.analysis_reports.append(report)
        tl = st.timeline
        if tl is not None and report.census:
            tl.collective_census(report.label, report.census)
        mem = getattr(report, "memory", None)
        if tl is not None and mem:
            tl.memory_census(report.label, mem)
        comm = getattr(report, "comm", None)
        if tl is not None and comm:
            tl.comm_census(report.label, comm)
    except Exception as e:  # pragma: no cover - publication is best-effort
        log.warning("HVD_ANALYZE: could not publish report: %s", e)


def wrap_optimizer(transformation, label: str = "DistributedOptimizer"):
    """Wrap an optax GradientTransformation so its first EAGER update
    triggers a jaxpr check of the equivalent in-trace reduction program.

    In-trace calls (leaves are tracers) are skipped — the surrounding
    ``shard_step`` hook analyzes the whole step there.  The analyzed
    program is the update as it compiles under the framework axis
    (``axis_env=[(mesh_axis, num_slots)]``), i.e. the psum-per-leaf data
    plane, which is also what the census reports.  Dedup is per wrapped
    instance + analysis generation (never by ``id()``, which the
    allocator recycles), so every optimizer gets its own check and an
    elastic re-init re-checks."""
    if not enabled():
        return transformation
    orig_update = transformation.update
    tag = f"{label}:{next(_instance_seq)}"
    done_gen = [None]  # generation at which this instance was analyzed

    def update(updates, state, params=None):
        if done_gen[0] != generation():
            if _maybe_analyze_update(orig_update, updates, state, params,
                                     tag):
                done_gen[0] = generation()
        return orig_update(updates, state, params)

    return transformation._replace(update=update)


def _maybe_analyze_update(orig_update, updates, state, params,
                          label: str) -> bool:
    """Returns True when an analysis actually ran (the caller then stops
    retrying); False for skip-for-now cases like in-trace calls."""
    if not enabled():
        return False
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(updates)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return False  # in-trace: the shard_step-level hook covers this
        from .. import core as _core
        if _core.is_initialized():
            axis = _core.mesh_axis()
            size = _core.num_slots()
        else:
            axis, size = "hvd", 1
    except Exception:
        return False
    analyze_traceable(
        lambda g: orig_update(g, state, params)[0], (updates,),
        label=label, axis_env=[(axis, size)],
        declared_axes=(axis,), once=False)
    return True
