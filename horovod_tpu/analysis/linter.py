"""hvdlint driver: source → AST rules → suppression-filtered findings.

Stdlib-only (ast + re); this module imports no jax, so the rules run
anywhere — only the jaxpr checker (jaxpr_check.py) needs the jax stack.

Suppression syntax (checked per finding line, plus file-wide):

* ``# hvdlint: disable=HVD001`` — suppress these rule IDs on this line
  (comma-separated list, or ``all``).
* ``# hvdlint: disable-file=HVD004`` — suppress for the whole file, on a
  comment line anywhere in the file.

Suppressed findings are still returned (``suppressed=True``) so tooling
can audit them; the CLI's exit code and the self-lint gate only count
unsuppressed ones.  A file that fails to parse produces a single HVD000
finding carrying the exception — the linter never raises on user input
(the loudly-but-gracefully contract).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from . import rules
from .findings import Finding

_PRAGMA = re.compile(
    r"#\s*hvdlint:\s*(disable|disable-file)\s*=\s*([A-Za-z0-9_,\s]+)")


def _parse_pragmas(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Map line → suppressed rule IDs, plus the file-wide suppression set.

    Only real COMMENT tokens count — pragma-shaped text inside a string
    literal or docstring (e.g. documentation of the suppression syntax)
    must not silence anything, so the source is tokenized rather than
    regex-scanned line by line.  A tokenize failure (theoretically
    unreachable once ast.parse succeeded) yields NO pragmas: findings
    stay loud rather than silently suppressed."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return per_line, file_wide
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA.search(tok.string)
        if not m:
            continue
        ids = {t.strip().upper() for t in m.group(2).split(",")
               if t.strip()}
        if m.group(1) == "disable-file":
            file_wide |= ids
        else:
            per_line.setdefault(tok.start[0], set()).update(ids)
    return per_line, file_wide


def _suppressed(f: Finding, per_line: Dict[int, Set[str]],
                file_wide: Set[str]) -> bool:
    def hit(ids: Set[str]) -> bool:
        return "ALL" in ids or f.rule in ids
    if hit(file_wide):
        return True
    ids = per_line.get(f.line)
    return ids is not None and hit(ids)


def _rule_selected(rule: str, select: Sequence[str],
                   ignore: Sequence[str]) -> bool:
    """Shared filter (findings.rule_selected): select wins when both are
    given, tokens match exactly or as prefixes (``--select HVD3``), and
    the contract applies uniformly to every pass and rule — including
    HVD000 analysis failures."""
    from .findings import rule_selected
    return rule_selected(rule, select, ignore)


def lint_source(source: str, path: str = "<string>",
                select: Sequence[str] = (),
                ignore: Sequence[str] = ()) -> List[Finding]:
    """Lint one source string.  ``select``/``ignore`` filter by rule ID."""
    try:
        tree = ast.parse(source, filename=path)
    except (SyntaxError, ValueError, RecursionError) as e:
        if not _rule_selected("HVD000", select, ignore):
            return []
        line = getattr(e, "lineno", 0) or 0
        col = (getattr(e, "offset", 0) or 0)
        return [Finding(rule="HVD000", path=path, line=line, col=max(col, 1),
                        message=f"could not parse: {type(e).__name__}: {e}")]
    findings = rules.analyze(tree, path)
    per_line, file_wide = _parse_pragmas(source)
    out: List[Finding] = []
    for f in findings:
        if not _rule_selected(f.rule, select, ignore):
            continue
        f.suppressed = _suppressed(f, per_line, file_wide)
        out.append(f)
    return out


def lint_file(path: str, select: Sequence[str] = (),
              ignore: Sequence[str] = ()) -> List[Finding]:
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
        source = raw.decode("utf-8", errors="replace")
    except OSError as e:
        if not _rule_selected("HVD000", select, ignore):
            return []
        return [Finding(rule="HVD000", path=path, line=0, col=1,
                        message=f"could not read file: {e}")]
    return lint_source(source, path=path, select=select, ignore=ignore)


_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "node_modules",
              "artifacts", ".venv", "venv", "build", "dist"}


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into a sorted, deduped .py file list.
    Nonexistent paths surface as HVD000 findings from lint_paths (not
    silently skipped)."""
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
    seen, uniq = set(), []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def lint_paths(paths: Iterable[str], select: Sequence[str] = (),
               ignore: Sequence[str] = ()) -> List[Finding]:
    """Lint every .py file under the given files/directories."""
    findings: List[Finding] = []
    files: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            if _rule_selected("HVD000", select, ignore):
                findings.append(Finding(
                    rule="HVD000", path=path, line=0, col=1,
                    message="path does not exist"))
        else:
            files.append(path)
    for f in iter_python_files(files):
        findings.extend(lint_file(f, select=select, ignore=ignore))
    return findings
