"""hvdlint command line: ``python -m horovod_tpu.analysis <paths>``.

Exit codes (CI contract, mirrored by tools/hvdlint.py and the
``hvdlint`` console script):

* 0 — no unsuppressed findings
* 1 — at least one unsuppressed finding (including HVD000 parse
  failures: a file the linter cannot read is a finding, not a crash)
* 2 — usage error (argparse) or internal analyzer error

Text output prints one block per finding (location, rule, severity,
message, fix hint); ``--format json`` prints a single machine-readable
object with the findings plus per-rule statistics.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from typing import List, Optional, Sequence

from .findings import RULES, unsuppressed
from .linter import lint_paths


def _split_ids(value: str) -> List[str]:
    return [tok.strip().upper() for tok in value.split(",") if tok.strip()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdlint",
        description="Distributed-correctness static analyzer for "
                    "horovod_tpu training code (rules HVD001-HVD009; "
                    "--race runs the hvdrace lock-order/thread-lifecycle "
                    "analysis, HVD200-HVD203; see docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint (default: .)")
    p.add_argument("--race", action="store_true",
                   help="run hvdrace instead: the lock-order & "
                        "thread-lifecycle analysis (rules HVD200-HVD203) "
                        "over the given paths as ONE global lock graph; "
                        "same output formats, pragmas, and exit codes")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", type=_split_ids, default=[],
                   help="comma-separated rule IDs to run exclusively")
    p.add_argument("--ignore", type=_split_ids, default=[],
                   help="comma-separated rule IDs to skip")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by '# hvdlint: "
                        "disable=...' pragmas")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def _print_rules() -> None:
    for rule in RULES.values():
        print(f"{rule.id} [{rule.severity}] {rule.summary}")
        print(f"    fix: {rule.fix_hint}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        _print_rules()
        return 0
    try:
        if args.race:
            from .lockgraph import analyze_paths
            findings = analyze_paths(args.paths, select=args.select,
                                     ignore=args.ignore)
        else:
            findings = lint_paths(args.paths, select=args.select,
                                  ignore=args.ignore)
    except Exception as e:  # internal error: distinct from "has findings"
        print(f"hvdlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    active = unsuppressed(findings)
    shown = findings if args.show_suppressed else active
    if args.format == "json":
        by_rule = Counter(f.rule for f in active)
        print(json.dumps({
            "findings": [f.to_dict() for f in shown],
            "summary": {
                "total": len(active),
                "suppressed": len(findings) - len(active),
                "by_rule": dict(sorted(by_rule.items())),
            },
        }, indent=1))
    else:
        for f in shown:
            print(f.format())
        suppressed_n = len(findings) - len(active)
        tail = f" ({suppressed_n} suppressed)" if suppressed_n else ""
        print(f"hvdlint: {len(active)} finding(s){tail} in "
              f"{len(set(f.path for f in findings)) if findings else 0} "
              f"flagged file(s)")
    return 1 if active else 0


def run_commandline() -> None:
    """Console-script entry point (pyproject [project.scripts] hvdlint)."""
    sys.exit(main())
