"""hvdlint command line: ``python -m horovod_tpu.analysis <paths>``.

Exit codes (CI contract, mirrored by tools/hvdlint.py and the
``hvdlint`` console script):

* 0 — no unsuppressed findings
* 1 — at least one unsuppressed finding (including HVD000 parse
  failures: a file the linter cannot read is a finding, not a crash)
* 2 — usage error (argparse) or internal analyzer error

Text output prints one block per finding (location, rule, severity,
message, fix hint); ``--format json`` prints a single machine-readable
object with the findings plus per-rule statistics.

Passes are registered in ONE table (``PASSES``): name → walker, rule
range, default paths.  Adding an analyzer means adding a row — the
dispatch, flag wiring, select/ignore filtering (prefix-matching:
``--select HVD3`` runs the whole HVD3xx family), pragma handling, and
the exit-code contract all come for free and stay identical across
lint (HVD0xx), ``--race`` (HVD2xx), ``--mem`` (HVD3xx), and ``--comm``
(HVD4xx).  ``--all`` runs every registered pass over ONE shared file
walk, prints the combined (per-pass) output, and exits with the MAX of
the per-pass exit codes — the one-invocation CI gate.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence

from .findings import RULES, unsuppressed


def _split_ids(value: str) -> List[str]:
    return [tok.strip().upper() for tok in value.split(",") if tok.strip()]


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------

def _run_lint(paths, select, ignore):
    from .linter import lint_paths
    return lint_paths(paths, select=select, ignore=ignore)


def _run_race(paths, select, ignore):
    from .lockgraph import analyze_paths
    return analyze_paths(paths, select=select, ignore=ignore)


def _run_mem(paths, select, ignore):
    from .memplan import analyze_paths
    return analyze_paths(paths, select=select, ignore=ignore)


def _run_comm(paths, select, ignore):
    from .shardplan import analyze_paths
    return analyze_paths(paths, select=select, ignore=ignore)


@dataclasses.dataclass(frozen=True)
class AnalyzerPass:
    """One analyzer: its CLI identity, rule family, and path walker."""

    name: str              # registry key; non-default passes get --<name>
    rules: str             # human-readable rule range for --help
    runner: Callable       # (paths, select, ignore) -> List[Finding]
    help: str
    default_paths: tuple = (".",)


PASSES: Dict[str, AnalyzerPass] = {
    "lint": AnalyzerPass(
        "lint", "HVD001-HVD009",
        _run_lint,
        "AST distributed-correctness rules (the default pass)"),
    "race": AnalyzerPass(
        "race", "HVD200-HVD203",
        _run_race,
        "hvdrace lock-order & thread-lifecycle analysis over the given "
        "paths as ONE global lock graph"),
    "mem": AnalyzerPass(
        "mem", "HVD300-HVD304",
        _run_mem,
        "hvdmem HBM donation hazards: donated-then-used reads and "
        "donatable-but-undonated jit args (the liveness walk itself "
        "runs trace-time under HVD_ANALYZE=1, docs/static_analysis.md)"),
    "comm": AnalyzerPass(
        "comm", "HVD400-HVD404",
        _run_comm,
        "hvdshard sharding/communication hazards: conflicting sharding "
        "annotations (implicit resharding) and dead mesh axes (the "
        "jaxpr sharding walk itself runs trace-time under "
        "HVD_ANALYZE=1, docs/static_analysis.md)"),
}
DEFAULT_PASS = "lint"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdlint",
        description="Distributed-correctness static analyzers for "
                    "horovod_tpu (default pass: AST lint HVD001-HVD011; "
                    "--race HVD200-HVD203; --mem HVD300-HVD304; "
                    "--comm HVD400-HVD404; --all runs every pass; see "
                    "docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories to analyze (default: .)")
    mode = p.add_mutually_exclusive_group()
    for name, pass_ in PASSES.items():
        if name == DEFAULT_PASS:
            continue
        mode.add_argument(
            f"--{name}", action="store_true",
            help=f"run the {name} pass instead ({pass_.rules}): "
                 f"{pass_.help}; same output formats, pragmas, and "
                 f"exit codes")
    mode.add_argument(
        "--all", action="store_true",
        help="run EVERY registered pass "
             f"({', '.join(PASSES)}) over one shared file walk; "
             "combined per-pass output, exit = max of per-pass exits")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", type=_split_ids, default=[],
                   help="comma-separated rule IDs (or prefixes: HVD3 "
                        "selects all HVD3xx) to run exclusively")
    p.add_argument("--ignore", type=_split_ids, default=[],
                   help="comma-separated rule IDs/prefixes to skip")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print findings silenced by '# hvdlint: "
                        "disable=...' pragmas")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    return p


def _print_rules() -> None:
    for rule in RULES.values():
        print(f"{rule.id} [{rule.severity}] {rule.summary}")
        print(f"    fix: {rule.fix_hint}")


def _run_all(args) -> int:
    """Every registered pass over ONE shared directory walk: the paths
    are expanded to a concrete .py file list once (``iter_python_files``
    is idempotent on files, so each runner reuses the walk instead of
    re-crawling), per-pass results render under their pass name, and
    the exit code is the MAX of the per-pass exits (2 internal error >
    1 findings > 0 clean)."""
    from .linter import iter_python_files
    paths = args.paths if args.paths else ["."]
    missing = [p for p in paths if not os.path.exists(p)]
    shared_walk = iter_python_files(
        [p for p in paths if os.path.exists(p)])
    results: Dict[str, dict] = {}
    exit_code = 0
    for name, pass_ in PASSES.items():
        try:
            findings = pass_.runner(shared_walk + missing, args.select,
                                    args.ignore)
        except Exception as e:
            print(f"hvdlint: internal error in pass '{name}': "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            exit_code = max(exit_code, 2)
            results[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        active = unsuppressed(findings)
        exit_code = max(exit_code, 1 if active else 0)
        shown = findings if args.show_suppressed else active
        results[name] = {
            "findings": [f.to_dict() for f in shown],
            "summary": {
                "total": len(active),
                "suppressed": len(findings) - len(active),
                "by_rule": dict(sorted(
                    Counter(f.rule for f in active).items())),
            },
        }
        if args.format != "json":
            for f in shown:
                print(f.format())
            suppressed_n = len(findings) - len(active)
            tail = f" ({suppressed_n} suppressed)" if suppressed_n else ""
            print(f"hvdlint [{name}]: {len(active)} finding(s){tail}")
    if args.format == "json":
        print(json.dumps({"pass": "all", "passes": results}, indent=1))
    return exit_code


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        _print_rules()
        return 0
    if args.all:
        try:
            return _run_all(args)
        except Exception as e:
            print(f"hvdlint: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
    chosen = [name for name in PASSES
              if name != DEFAULT_PASS and getattr(args, name, False)]
    pass_ = PASSES[chosen[0] if chosen else DEFAULT_PASS]
    paths = args.paths if args.paths else list(pass_.default_paths)
    try:
        findings = pass_.runner(paths, args.select, args.ignore)
    except Exception as e:  # internal error: distinct from "has findings"
        print(f"hvdlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    active = unsuppressed(findings)
    shown = findings if args.show_suppressed else active
    if args.format == "json":
        by_rule = Counter(f.rule for f in active)
        print(json.dumps({
            "pass": pass_.name,
            "findings": [f.to_dict() for f in shown],
            "summary": {
                "total": len(active),
                "suppressed": len(findings) - len(active),
                "by_rule": dict(sorted(by_rule.items())),
            },
        }, indent=1))
    else:
        for f in shown:
            print(f.format())
        suppressed_n = len(findings) - len(active)
        tail = f" ({suppressed_n} suppressed)" if suppressed_n else ""
        print(f"hvdlint: {len(active)} finding(s){tail} in "
              f"{len(set(f.path for f in findings)) if findings else 0} "
              f"flagged file(s)")
    return 1 if active else 0


def run_commandline() -> None:
    """Console-script entry point (pyproject [project.scripts] hvdlint)."""
    sys.exit(main())
