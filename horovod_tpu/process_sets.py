"""Process sets — collectives over rank subgroups, mapped to XLA replica groups.

Reference: a process set is a subgroup of ranks with its own controller,
tensor queue, response cache and sub-communicators
(horovod/common/process_set.h:26,89); the Python surface is
horovod/common/process_sets.py:18 (``ProcessSet``, ``global_process_set``,
``add_process_set``, ``remove_process_set``) and registration happens in
operations.cc:359,1262-1405 with dynamic add/remove gated by
``HOROVOD_DYNAMIC_PROCESS_SETS``.

TPU mapping: a process set over slot ranks becomes a static ``members`` tuple
burned into the traced collective (ops/collective_ops.py lowers subsets via
masked full-axis collectives, since XLA replica groups must form an equal-size
partition of the axis).  The compiled program stays total over the mesh as
SPMD requires; members get the group result, non-members keep their own value.
Dynamic sets need no re-rendezvous: registering a set only changes the members
burned into subsequently-traced programs (recompile on first use — see
SURVEY.md §7 "Process sets ↔ replica groups").
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from . import core as _core


class ProcessSet:
    """Subgroup of slot ranks (horovod/common/process_sets.py:18 analog).

    Construct with an iterable of global slot ranks.  ``process_set_id`` is
    assigned at registration (0 is the global set).
    """

    process_set_id: Optional[int]

    def __init__(self, ranks: Optional[Sequence[int]] = None,
                 mpi_comm=None):
        """``ranks``: global slot ranks.  ``mpi_comm``: an mpi4py
        communicator — its group's translated global ranks define the set
        (reference: ProcessSet(mpi_comm), process_sets.py:18); requires
        mpi4py at call time."""
        self.process_set_id = None
        if mpi_comm is not None:
            if ranks is not None:
                raise ValueError("pass either ranks or mpi_comm, not both")
            try:
                from mpi4py import MPI
            except ImportError as e:
                raise ImportError(
                    "ProcessSet(mpi_comm=...) requires mpi4py; on TPU pass "
                    "the rank list instead") from e
            group = mpi_comm.Get_group()
            world = MPI.COMM_WORLD.Get_group()
            ranks = MPI.Group.Translate_ranks(
                group, list(range(group.Get_size())), world)
        self.ranks: Optional[List[int]] = (
            sorted(set(int(r) for r in ranks)) if ranks is not None else None)

    def size(self) -> Optional[int]:
        """Number of ranks in the set (None before init for the global set)."""
        if self.ranks is not None:
            return len(self.ranks)
        if _core.is_initialized():
            return _core.num_slots()
        return None

    def rank(self) -> Optional[int]:
        """This process's rank within the set, or None if excluded.

        In emulated / single-controller mode the notion is per-slot; the
        process-level answer uses slot 0 of this process, matching the
        reference where process == slot."""
        if not _core.is_initialized():
            return None
        my = _core.rank()
        if self.ranks is None:
            return my
        if my in self.ranks:
            return self.ranks.index(my)
        return None

    def included(self) -> bool:
        return self.rank() is not None

    def _resolved_ranks(self) -> List[int]:
        if self.ranks is None:
            return list(range(_core.num_slots()))
        return self.ranks

    def members(self) -> Optional[tuple]:
        """Static member tuple for the collective layer, or None for the full
        axis.  XLA replica groups must form an equal-size partition of the
        axis, which arbitrary subsets don't satisfy — so subsets are lowered
        via the mask formulation in ops/collective_ops.py instead."""
        n = _core.num_slots()
        resolved = self._resolved_ranks()
        if len(resolved) == n:
            return None  # full axis — fast un-grouped form
        return tuple(resolved)

    def __repr__(self):
        return (f"ProcessSet(id={self.process_set_id}, "
                f"ranks={self.ranks if self.ranks is not None else 'global'})")


class ProcessSetTable:
    """id → ProcessSet registry (process_set.h ProcessSetTable analog).

    Ids are assigned densely and never reused within a session, matching the
    reference's stable-id contract that the response cache keys depend on."""

    def __init__(self, num_slots: int):
        self._lock = threading.Lock()
        self._next_id = 1
        self.num_slots = num_slots
        self.table: Dict[int, ProcessSet] = {}
        g = ProcessSet()
        g.process_set_id = 0
        self.table[0] = g

    @property
    def global_set(self) -> ProcessSet:
        return self.table[0]

    def register(self, ps: ProcessSet) -> ProcessSet:
        with self._lock:
            if ps.process_set_id is not None:
                return ps
            ranks = ps._resolved_ranks() if ps.ranks is not None else None
            if ranks is not None:
                if not ranks:
                    raise ValueError("process set must contain at least one rank")
                if ranks[-1] >= self.num_slots or ranks[0] < 0:
                    raise ValueError(
                        f"process set ranks {ranks} out of range for "
                        f"{self.num_slots} slots")
                # Reference semantics: an existing identical set is returned
                # rather than duplicated (operations.cc:1262 add returns the
                # existing id).
                for existing in self.table.values():
                    if existing.ranks == ranks:
                        ps.process_set_id = existing.process_set_id
                        return existing
            ps.process_set_id = self._next_id
            self._next_id += 1
            self.table[ps.process_set_id] = ps
            return ps

    def remove(self, ps: ProcessSet) -> None:
        with self._lock:
            if ps.process_set_id == 0:
                raise ValueError(
                    "cannot remove the global process set (process_set.h)")
            self.table.pop(ps.process_set_id, None)
            ps.process_set_id = None

    def get(self, process_set_id: int) -> ProcessSet:
        try:
            return self.table[process_set_id]
        except KeyError:
            raise ValueError(f"unknown process set id {process_set_id}")


# Module-level convenience API mirroring horovod/common/process_sets.py.
global_process_set = ProcessSet()
global_process_set.process_set_id = 0


def _table() -> ProcessSetTable:
    st = _core._require_init()
    return st.process_set_table


def add_process_set(process_set) -> ProcessSet:
    """Register a new process set after init
    (horovod/common/process_sets.py add_process_set).  Accepts a ProcessSet
    or a plain rank list."""
    if not isinstance(process_set, ProcessSet):
        process_set = ProcessSet(process_set)
    return _table().register(process_set)


def remove_process_set(process_set: ProcessSet) -> bool:
    """Deregister (dynamic process sets)."""
    try:
        _table().remove(process_set)
        return True
    except (ValueError, KeyError):
        return False


def process_set_included(process_set_id: int = 0) -> bool:
    return _table().get(process_set_id).included()


def get_process_set_ids() -> List[int]:
    return sorted(_table().table.keys())


def partition_process_sets(num_groups: int) -> List[ProcessSet]:
    """Register ``num_groups`` disjoint contiguous process sets covering
    every slot (TPU extension; no reference analog — the reference has no
    built-in partitioner).  Slots are dealt contiguously so each group's
    members are ICI torus neighbors; a ragged remainder is spread one
    slot at a time over the leading groups.  A single group spans the
    full axis and lowers to the un-grouped fast path (members() → None).

    Primary consumer: ``serve.replica.build_replicas`` maps independent
    serving replicas onto the groups; also a convenient way to build
    hierarchical-collective islands.
    """
    n = _core.num_slots()
    if num_groups < 1 or num_groups > n:
        raise ValueError(
            f"cannot partition {n} slots into {num_groups} groups")
    base, extra = divmod(n, num_groups)
    sets, start = [], 0
    for g in range(num_groups):
        width = base + (1 if g < extra else 0)
        sets.append(add_process_set(list(range(start, start + width))))
        start += width
    return sets
