"""Elastic training on Ray: autoscaler-driven discovery + actor workers.

Reference: horovod/ray/elastic_v2.py — RayHostDiscovery (:40) turns the
Ray cluster's alive-node resource view into the {hostname: slots} dict the
ElasticDriver consumes; ElasticAdapter (:197) spawns one Ray actor per
assigned slot with the elastic rendezvous env and feeds worker exits back
to the driver, so Ray autoscaler events (nodes appearing/disappearing)
become elastic scale-up/scale-down.

This build reuses the SAME ElasticDriver/HostManager/registry as the CLI
elastic path (horovod_tpu/elastic/driver.py) — only discovery (Ray node
state) and the worker launch (Ray actors instead of local/ssh processes)
differ.  The actor-spawn layer is injectable (``spawn_fn``) so the wiring
is unit-testable with a fake cluster (reference pattern:
test/single/test_ray_elastic_v2.py with mocked execution).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import config as _config
from .elastic import coordinator_port_for
from .elastic.discovery import HostDiscovery
from .elastic.driver import ElasticDriver
from .runner import hosts as _hosts
from .runner.http_server import RendezvousServer
from .utils import get_logger


def _require_ray():
    try:
        import ray  # noqa: F401
        return ray
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.ray_elastic requires the 'ray' package "
            "(pip install ray); the core framework does not depend on it"
        ) from e


class RayHostDiscovery(HostDiscovery):
    """Maps the Ray cluster's alive nodes to {hostname: slots}
    (elastic_v2.py:40 RayHostDiscovery).

    Slots per node = GPU count / gpus_per_worker when ``use_gpu``, else
    TPU resource / tpu_per_worker when ``tpu_per_worker``, else
    CPU count / cpus_per_worker."""

    def __init__(self, use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: int = 1, tpu_per_worker: int = 0):
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker
        self.tpu_per_worker = tpu_per_worker

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        ray = _require_ray()
        result: Dict[str, int] = {}
        for node in ray.nodes():
            if not node.get("Alive", False):
                continue
            resources = node.get("Resources", {}) or {}
            hostname = node.get("NodeManagerHostname") or \
                node.get("NodeManagerAddress")
            if not hostname:
                continue
            if self.tpu_per_worker:
                slots = int(resources.get("TPU", 0) // self.tpu_per_worker)
            elif self.use_gpu:
                slots = int(resources.get("GPU", 0) //
                            max(self.gpus_per_worker, 1))
            else:
                slots = int(resources.get("CPU", 0) //
                            max(self.cpus_per_worker, 1))
            if slots > 0:
                result[hostname] = result.get(hostname, 0) + slots
        return result


def _worker_entry(fn, args, kwargs):
    """Runs INSIDE the worker: executes the user fn, then reports the
    worker's FINAL (world_version, rank, size) — a survivor's rank/world
    change across resets (elastic/__init__.py _refresh_world_from_rendezvous
    refreshes the env), so the spawn-time slot cannot key the result."""
    import os
    value = fn(*args, **(kwargs or {}))
    return (int(os.environ.get("HVD_TPU_WORLD_VERSION", "0")),
            int(os.environ.get(_config.HOROVOD_RANK, "0")),
            int(os.environ.get(_config.HOROVOD_SIZE, "1")),
            value)


class _RayActorHandle:
    """Default spawn layer: one Ray actor pinned to the slot's node."""

    def __init__(self, fn, args, kwargs, env: Dict[str, str],
                 hostname: str, opts: dict):
        ray = _require_ray()

        @ray.remote(**opts)
        class _ElasticWorker:
            def run(self, env, fn, args, kwargs):
                import os
                os.environ.update(env)
                return fn(*args, **(kwargs or {}))

        # Soft node affinity: the slot was assigned to this hostname by the
        # driver (elastic_v2.py _create_resources node_id resource pinning).
        try:
            from ray.util.scheduling_strategies import \
                NodeAffinitySchedulingStrategy
            for node in ray.nodes():
                if node.get("Alive") and \
                        (node.get("NodeManagerHostname") == hostname or
                         node.get("NodeManagerAddress") == hostname):
                    opts = dict(opts, scheduling_strategy=
                                NodeAffinitySchedulingStrategy(
                                    node_id=node["NodeID"], soft=True))
                    break
        except Exception:  # older ray: fall back to default scheduling
            pass
        self._actor = _ElasticWorker.options(**opts).remote() \
            if hasattr(_ElasticWorker, "options") else _ElasticWorker.remote()
        self._ref = self._actor.run.remote(env, fn, args, kwargs)
        self._result = None

    def wait(self, timeout: float) -> bool:
        """True when finished (result or failure)."""
        ray = _require_ray()
        done, _ = ray.wait([self._ref], timeout=timeout)
        return bool(done)

    def result(self) -> Tuple[int, Any]:
        """(exit_code, result) — nonzero when the actor died/raised."""
        ray = _require_ray()
        try:
            return 0, ray.get(self._ref)
        except Exception as e:
            get_logger().warning("ray elastic worker failed: %s", e)
            return 1, None

    def kill(self) -> None:
        ray = _require_ray()
        try:
            ray.kill(self._actor)
        except Exception:
            pass


class ElasticRayExecutor:
    """Elastic executor on Ray (elastic_v2.py:197 ElasticAdapter; v1 API
    name ElasticRayExecutor).

    Usage::

        executor = ElasticRayExecutor(min_workers=1, max_workers=4)
        executor.start()
        results = executor.run(train_fn)   # train_fn uses hvd.elastic.run
        executor.shutdown()
    """

    def __init__(self,
                 settings: Optional[dict] = None,
                 min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 cooldown_range: Optional[Tuple[float, float]] = None,
                 cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 gpus_per_worker: int = 0,
                 tpu_per_worker: int = 0,
                 elastic_timeout: float = 600.0,
                 override_discovery: Optional[HostDiscovery] = None,
                 spawn_fn: Optional[Callable] = None,
                 extra_env_vars: Optional[Dict[str, str]] = None):
        self.settings = settings or {}
        self.min_workers = min_workers
        self.max_workers = max_workers or min_workers
        self.reset_limit = reset_limit
        self.cooldown_range = cooldown_range
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self.gpus_per_worker = gpus_per_worker
        self.tpu_per_worker = tpu_per_worker
        self.elastic_timeout = elastic_timeout
        self.extra_env_vars = dict(extra_env_vars or {})
        self._discovery = override_discovery
        self._spawn_fn = spawn_fn  # injectable for tests / other backends
        self._rendezvous: Optional[RendezvousServer] = None
        self._driver: Optional[ElasticDriver] = None
        self._addr: Optional[str] = None
        self._port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Start the rendezvous server and the elastic driver
        (elastic_v2.py ElasticAdapter.start)."""
        if self._discovery is None:
            self._discovery = RayHostDiscovery(
                use_gpu=self.use_gpu, cpus_per_worker=self.cpus_per_worker,
                gpus_per_worker=self.gpus_per_worker,
                tpu_per_worker=self.tpu_per_worker)
        self._rendezvous = RendezvousServer()
        self._port = self._rendezvous.start()
        self._addr = socket.gethostbyname(socket.gethostname())
        self._driver = ElasticDriver(
            self._rendezvous, self._discovery,
            self.min_workers, self.max_workers,
            reset_limit=self.reset_limit,
            cooldown_range=self.cooldown_range,
            timeout=self.elastic_timeout)

    def _worker_env(self, slot: _hosts.SlotInfo, world_version: int) -> Dict:
        from .elastic.launch_support import slot_env
        return {
            **slot_env(slot, world_version, self._addr, self._port,
                       self._driver, coord_base=self._port + 1),
            **self.extra_env_vars,
        }

    def _default_spawn(self, fn, args, kwargs, env, slot):
        opts = {"num_cpus": self.cpus_per_worker}
        if self.use_gpu or self.gpus_per_worker:
            opts["num_gpus"] = self.gpus_per_worker or 1
        if self.tpu_per_worker:
            opts["resources"] = {"TPU": self.tpu_per_worker}
        return _RayActorHandle(fn, args, kwargs, env, slot.hostname, opts)

    def run(self, fn: Callable, args: tuple = (),
            kwargs: Optional[dict] = None) -> List[Any]:
        """Launch the elastic world and block until it settles; returns the
        FINAL world's per-rank results ordered by rank (elastic_v2.py run).
        ``fn`` should wrap its training loop in ``hvd.elastic.run`` to
        survive reshapes."""
        import functools
        if self._driver is None:
            self.start()
        driver = self._driver
        spawn = self._spawn_fn or self._default_spawn
        entry = functools.partial(_worker_entry, fn, args, kwargs)
        results: Dict[Tuple[int, int], Any] = {}  # (version, rank) -> value
        results_lock = threading.Lock()

        def worker_fn(slot: _hosts.SlotInfo,
                      terminate_event: threading.Event,
                      world_version: int) -> int:
            env = self._worker_env(slot, world_version)
            handle = spawn(entry, (), {}, env, slot)
            while not handle.wait(timeout=0.25):
                if terminate_event.is_set():
                    handle.kill()
                    return 143
            code, value = handle.result()
            if code == 0:
                ver, rank, _size, v = value
                with results_lock:
                    results[(ver, rank)] = v
            return code

        driver.start(worker_fn)
        driver.join()
        if driver.error_message:
            raise RuntimeError(driver.error_message)
        states = driver.registry.last_rank_states()
        failed = [k for k, v in states.items() if v == "FAILURE"]
        if failed:
            raise RuntimeError(
                f"ray elastic run finished with failed slots: {failed}")
        final = driver.world_version
        with results_lock:
            final_results = {r: v for (ver, r), v in results.items()
                             if ver == final}
        return [final_results[r] for r in sorted(final_results)]

    def shutdown(self) -> None:
        if self._driver is not None:
            self._driver.stop()
            self._driver = None
        if self._rendezvous is not None:
            self._rendezvous.stop()
            self._rendezvous = None
