"""Exception hierarchy for the elastic/fault-tolerance contract.

Mirrors the reference semantics of horovod/common/exceptions.py:18,26: a failed
collective raises ``HorovodInternalError`` which the elastic ``run`` wrapper
catches to restore state from the last commit; a host-membership change raises
``HostsUpdatedInterrupt`` which commits and re-initializes without state loss.
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective routine fails.

    Under ``horovod_tpu.elastic.run`` this triggers ``state.restore()`` from the
    last in-memory commit followed by re-initialization over the surviving hosts.
    """


class CollectiveRejectedError(HorovodInternalError):
    """A coordinator-published error verdict for a negotiated collective
    (the ERROR Response of controller.cc ConstructResponse).

    Distinct from other ``HorovodInternalError``s because a rejection is
    SYMMETRIC: every participating rank raised it, so nobody entered the
    device collective — a joined rank's replay loop may log it and keep
    servicing, whereas a local timeout must propagate."""


class RendezvousUnreachableError(HorovodInternalError):
    """The launcher's rendezvous KV server refused connections for a
    sustained window — the launcher is presumed dead.  Unlike a transient
    reset failure this is NOT retried: without a rendezvous there is no
    world to rejoin, so the worker terminates promptly instead of polling
    out the full elastic timeout."""


class HostsUpdatedInterrupt(Exception):
    """Raised when the set of participating hosts changes mid-training.

    ``skip_sync`` is True when the update does not require re-broadcasting state
    (pure scale-up discovered before any rank failed).
    """

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodVersionMismatchError(ImportError):
    """Raised when launcher and worker framework versions disagree."""


class TensorShapeMismatchError(ValueError):
    """Raised when ranks submit mismatched shapes to one named collective.

    The reference detects this in the coordinator's ``ConstructResponse``
    (controller.cc:496) and delivers an error Response to every rank's status
    callback; here it surfaces as an exception from the negotiation layer.
    """


class DuplicateNameError(ValueError):
    """Two in-flight collectives share one name (common.h:239 DUPLICATE_NAME_ERROR)."""
