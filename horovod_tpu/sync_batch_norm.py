"""Synchronized batch normalization across ranks.

Reference: horovod/tensorflow/sync_batch_norm.py:22 (SyncBatchNormalization:
allreduces batch mean and variance across ranks inside the layer) and the
torch equivalent.  On TPU the statistics reduction is a psum over the mesh
axis inside the compiled step — the same pattern flax's BatchNorm supports
via ``axis_name``; this module provides (a) the raw stats reduction for
custom layers and (b) a flax module preconfigured for the framework axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .process_sets import ProcessSet, global_process_set


def sync_batch_stats(x: jax.Array,
                     *,
                     axis_name: str = "hvd",
                     reduction_axes=None,
                     process_set: Optional[ProcessSet] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Cross-rank batch mean/variance (sync_batch_norm.py:22 semantics).

    Computes E[x] and Var[x] over the local reduction axes *and* the mesh
    axis, using the E[x^2]-E[x]^2 form so one fused psum of (sum, sumsq,
    count) crosses ICI — the reference allreduces mean and variance
    separately; fusing into one collective is the TPU-native improvement."""
    if reduction_axes is None:
        reduction_axes = tuple(range(x.ndim - 1))  # all but features
    members = process_set.members() if process_set is not None else None
    groups = None
    n_local = 1
    for a in reduction_axes:
        n_local *= x.shape[a]
    s = jnp.sum(x, axis=reduction_axes)
    sq = jnp.sum(jnp.square(x), axis=reduction_axes)
    cnt = jnp.asarray(n_local, x.dtype)
    from .ops import collective_ops as C
    s, sq, cnt = (C.allreduce(v, C.Sum, axis_name=axis_name, members=members)
                  for v in (s, sq, cnt))
    mean = s / cnt
    var = sq / cnt - jnp.square(mean)
    return mean, var


def SyncBatchNorm(**kwargs):
    """flax.linen.BatchNorm preconfigured to synchronize statistics over the
    framework mesh axis (the flax-native equivalent of
    hvd.SyncBatchNormalization).  Accepts all flax BatchNorm kwargs."""
    import flax.linen as nn
    kwargs.setdefault("axis_name", "hvd")
    kwargs.setdefault("use_running_average", None)
    return nn.BatchNorm(**kwargs)
