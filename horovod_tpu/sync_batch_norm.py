"""Synchronized batch normalization across ranks.

Reference: horovod/tensorflow/sync_batch_norm.py:22 (SyncBatchNormalization:
allreduces batch mean and variance across ranks inside the layer) and the
torch equivalent.  On TPU the statistics reduction is a psum over the mesh
axis inside the compiled step — the same pattern flax's BatchNorm supports
via ``axis_name``; this module provides (a) the raw stats reduction for
custom layers and (b) a flax module preconfigured for the framework axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .process_sets import ProcessSet


def sync_batch_stats(x: jax.Array,
                     *,
                     axis_name: str = "hvd",
                     reduction_axes=None,
                     process_set: Optional[ProcessSet] = None
                     ) -> Tuple[jax.Array, jax.Array]:
    """Cross-rank batch mean/variance (sync_batch_norm.py:22 semantics).

    Computes E[x] and Var[x] over the local reduction axes *and* the mesh
    axis, using the E[x^2]-E[x]^2 form so one fused psum of (sum, sumsq,
    count) crosses ICI — the reference allreduces mean and variance
    separately; fusing into one collective is the TPU-native improvement."""
    if reduction_axes is None:
        reduction_axes = tuple(range(x.ndim - 1))  # all but features
    members = process_set.members() if process_set is not None else None
    n_local = 1
    for a in reduction_axes:
        n_local *= x.shape[a]
    s = jnp.sum(x, axis=reduction_axes)
    sq = jnp.sum(jnp.square(x), axis=reduction_axes)
    from .ops import collective_ops as C
    # Flatten so ANY reduction_axes (stats of any rank) ride the single
    # collective; reshape back after the split.
    shape, k = s.shape, s.size
    vec = jnp.concatenate([s.ravel(), sq.ravel(),
                           jnp.full((1,), n_local, x.dtype)])
    vec = C.allreduce(vec, C.Sum, axis_name=axis_name, members=members)
    s, sq, cnt = (vec[:k].reshape(shape), vec[k:2 * k].reshape(shape),
                  vec[-1])
    mean = s / cnt
    # Clamp: the E[x^2]-E[x]^2 form can go epsilon-negative in finite
    # precision, and rsqrt(var + eps) downstream must not see it.
    var = jnp.maximum(sq / cnt - jnp.square(mean), 0.0)
    return mean, var


def FusedBatchNorm(**kwargs):
    """Batch norm with float32 statistics and a bf16-foldable epilogue —
    the TPU-shaped batch norm (flax-compatible param/stat tree).

    Why not ``flax.linen.BatchNorm(dtype=float32)`` (what the ResNet ran
    through round 4): that layer upcasts the WHOLE activation tensor to
    f32 for the normalize chain, so every BN in the net pays full-tensor
    bf16->f32->bf16 converts and an f32 elementwise pass — the
    "convert/multiply_reduce fusions ~0.5-1 ms each" in the round-2
    profile (artifacts/PERF_r02.md).  ``BatchNorm(dtype=bfloat16)`` fixes
    the bandwidth but computes the STATISTICS in bf16, which is numerically
    unacceptable.  This layer splits the two concerns:

    * statistics: one multi-output f32 reduction (sum, sum-of-squares) —
      and under ``axis_name`` ONE psum of the concatenated
      (sum, sumsq, count) vector (the reference's SyncBatchNormalization,
      tensorflow/sync_batch_norm.py:22, allreduces mean and variance
      separately);
    * application: the per-channel scale/offset are FOLDED in f32
      (``a = gamma*rsqrt(var+eps)``, ``b = beta - mean*a``) and applied as
      a pure-bf16 ``x*a + b`` — an elementwise op XLA fuses with the
      surrounding ReLU / residual add / conv epilogue instead of a
      standalone f32 normalize kernel (VERDICT r4 next-step #5; pinned by
      tests/test_models.py's compiled-HLO kernel-count check).

    A plain factory returning a flax module instance (the class is built
    lazily so importing this file does not import flax)."""
    return _fused_bn_cls()(**kwargs)


def _fused_bn_cls():
    global _FusedBatchNorm
    if _FusedBatchNorm is not None:
        return _FusedBatchNorm

    import flax.linen as nn
    from typing import Any, Callable

    # NOTE: named ``BatchNorm`` so flax's auto-naming produces the same
    # submodule keys ("BatchNorm_0", ...) as flax.linen.BatchNorm — the
    # fused layer is checkpoint-compatible drop-in, tree keys included.
    class BatchNorm(nn.Module):
        use_running_average: Optional[bool] = None
        axis_name: Optional[str] = None
        momentum: float = 0.99
        epsilon: float = 1e-5
        dtype: Optional[Any] = None   # apply dtype; default = input dtype
        use_bias: bool = True
        use_scale: bool = True
        bias_init: Callable = nn.initializers.zeros
        scale_init: Callable = nn.initializers.ones

        @nn.compact
        def __call__(self, x, use_running_average: Optional[bool] = None):
            ura = nn.merge_param("use_running_average",
                                 self.use_running_average,
                                 use_running_average)
            feat = x.shape[-1]
            reduction_axes = tuple(range(x.ndim - 1))
            ra_mean = self.variable("batch_stats", "mean",
                                    lambda: jnp.zeros((feat,), jnp.float32))
            ra_var = self.variable("batch_stats", "var",
                                   lambda: jnp.ones((feat,), jnp.float32))
            scale = self.param("scale", self.scale_init, (feat,),
                               jnp.float32) if self.use_scale else None
            bias = self.param("bias", self.bias_init, (feat,),
                              jnp.float32) if self.use_bias else None
            if ura:
                mean, var = ra_mean.value, ra_var.value
            else:
                xf = x.astype(jnp.float32)
                if self.axis_name is not None and \
                        not self.is_initializing():
                    # ONE collective for the whole stats exchange (flax
                    # likewise skips the collective during init); the
                    # concat-psum lives in sync_batch_stats — one
                    # implementation of the exchange, not two.
                    mean, var = sync_batch_stats(
                        xf, axis_name=self.axis_name,
                        reduction_axes=reduction_axes)
                else:
                    mean = jnp.mean(xf, axis=reduction_axes)
                    var = jnp.maximum(
                        jnp.mean(jnp.square(xf), axis=reduction_axes)
                        - jnp.square(mean), 0.0)
                if not self.is_initializing():
                    m = self.momentum
                    ra_mean.value = m * ra_mean.value + (1 - m) * mean
                    ra_var.value = m * ra_var.value + (1 - m) * var
            a = lax.rsqrt(var + self.epsilon)
            if scale is not None:
                a = a * scale
            b = -mean * a
            if bias is not None:
                b = b + bias
            # dtype=None matches flax BatchNorm's promotion (bf16 input +
            # f32 params -> f32 output), so drop-in users keep their dtype
            # contract; passing an explicit bf16 dtype is the opt-in for
            # the folded bf16 epilogue (what the ResNet does).
            dtype = self.dtype if self.dtype is not None else \
                jnp.promote_types(x.dtype, jnp.float32)
            return x.astype(dtype) * a.astype(dtype) + b.astype(dtype)

    _FusedBatchNorm = BatchNorm
    return BatchNorm


_FusedBatchNorm = None


#: FusedBatchNorm's full kwarg surface (SyncBatchNorm routes here when the
#: caller stays inside it, and to flax BatchNorm otherwise).
_FUSED_KWARGS = frozenset({
    "use_running_average", "axis_name", "momentum", "epsilon", "dtype",
    "use_bias", "use_scale", "bias_init", "scale_init", "name", "parent"})


def SyncBatchNorm(**kwargs):
    """Batch norm synchronized over the framework mesh axis (the
    hvd.SyncBatchNormalization analog, tensorflow/sync_batch_norm.py:22).

    Common configurations get :class:`FusedBatchNorm` (repo-owned: f32
    one-psum stats, foldable application); flax-only kwargs the fused
    layer does not implement (``axis``, ``axis_index_groups``,
    ``param_dtype``, ``use_fast_variance``, ...) keep the documented
    "accepts all flax BatchNorm kwargs" contract by falling back to
    ``flax.linen.BatchNorm`` with the mesh axis preconfigured."""
    kwargs.setdefault("axis_name", "hvd")
    kwargs.setdefault("use_running_average", None)
    if set(kwargs) <= _FUSED_KWARGS:
        return FusedBatchNorm(**kwargs)
    import flax.linen as nn
    return nn.BatchNorm(**kwargs)
