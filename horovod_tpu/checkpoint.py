"""Checkpoint helpers: the rank-0-writes / broadcast-on-load convention.

Reference behavior (SURVEY.md §5.4): Horovod standardizes (a)
broadcast_variables / broadcast_object so rank 0's restored checkpoint
reaches all ranks, (b) "only rank 0 writes to disk" in every example.  This
module packages that convention over orbax (the JAX checkpointing library):
``save`` writes from rank 0 only; ``restore`` loads on rank 0 and
broadcasts, so a freshly-resized elastic world restores consistently.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from . import core as _core
from . import functions as _functions


def _ckptr():
    import orbax.checkpoint as ocp
    if jax.process_count() > 1:
        # Rank-0-writes convention: only the CALLING process participates
        # in the save/restore.  Orbax's default save()/restore() run
        # multihost sync barriers spanning every process; with only rank 0
        # inside orbax and the other ranks waiting at OUR release barrier,
        # the two barriers deadlock (30 s Gloo DEADLINE_EXCEEDED).  Scope
        # orbax's sync to this process alone.
        from orbax.checkpoint import options as _opts
        idx = jax.process_index()
        return ocp.Checkpointer(
            ocp.PyTreeCheckpointHandler(),
            multiprocessing_options=_opts.MultiprocessingOptions(
                primary_host=idx, active_processes={idx}))
    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, force: bool = True,
         _rank0_post=None) -> None:
    """Write ``state`` (pytree) from rank 0 only; other ranks no-op and
    wait at a barrier so nobody races ahead of an incomplete write.
    ``_rank0_post`` runs on rank 0 after the write but BEFORE the barrier,
    so sidecar files are in place before any rank is released to read."""
    from . import ops as _ops
    try:
        if _core.rank() == 0:
            _ckptr().save(os.path.abspath(path), jax.device_get(state),
                          force=force)
            if _rank0_post is not None:
                _rank0_post()
    finally:
        # The barrier must run even when the rank-0 write raises: the
        # other ranks are already blocking in it (no timeout), so skipping
        # it would turn a local write failure into a distributed hang.
        if _core.size() > 1 and not _core._require_init().topology.emulated:
            _ops.barrier()


def save_model(path: str, params: Any, opt_state: Any = None,
               extra: Optional[dict] = None) -> None:
    """Persist a trained model WITH its (possibly DistributedOptimizer-
    wrapped) optimizer state, so retraining resumes the exact trajectory —
    the analog of saving a Keras model whose optimizer weights ride along
    (reference keras/__init__.py:268 load_model contract).  Rank-0-writes
    semantics of :func:`save` apply."""
    def write_sidecar():
        # Metadata rides NEXT TO the orbax tree (not inside it): arbitrary
        # user dicts would force restore templates to predeclare their
        # structure; a JSON sidecar + broadcast_object on load avoids that.
        # Written before save()'s barrier releases the other ranks, so a
        # coordinated immediate load_model always sees it.
        import json
        with open(os.path.join(os.path.abspath(path), "extra.json"),
                  "w") as f:
            json.dump(extra or {}, f)

    save(path, {"params": params, "opt_state": opt_state},
         _rank0_post=write_sidecar)


def load_model(path: str, optimizer=None, params_template: Any = None,
               broadcast: bool = True, **wrap_kwargs):
    """Load a model saved by :func:`save_model` and re-wrap its optimizer
    in ``DistributedOptimizer`` so the restored state (momenta, adam
    moments, local-aggregation counters) is picked up for retraining —
    the reference's ``hvd.load_model`` wraps the deserialized Keras
    optimizer the same way (keras/__init__.py:268 wrap_optimizer).

    ``optimizer`` is the BASE optax optimizer (as originally passed to
    DistributedOptimizer); ``wrap_kwargs`` forward to DistributedOptimizer
    (backward_passes_per_step, compression, op, ...).  ``params_template``
    supplies pytree structure for non-root ranks / orbax; rank 0 alone may
    omit it on a single-process restore.

    Returns ``(params, opt, opt_state, extra)`` where ``opt`` is the
    wrapped optimizer ready for ``opt.update``."""
    from .optimizer import DistributedOptimizer
    opt = None
    template = None
    if optimizer is not None:
        opt = DistributedOptimizer(optimizer, **wrap_kwargs)
        if params_template is not None:
            template = {"params": params_template,
                        "opt_state": opt.init(params_template)}
    restored = restore(path, template=template, broadcast=broadcast)
    extra = None
    if _core.rank() == 0 or not broadcast:
        import json
        extra_path = os.path.join(os.path.abspath(path), "extra.json")
        if os.path.exists(extra_path):
            with open(extra_path) as f:
                extra = json.load(f)
    topo = _core._require_init().topology
    if broadcast and topo.size > 1 and not topo.emulated:
        extra = _functions.broadcast_object(extra, root_rank=0)
    return restored["params"], opt, restored.get("opt_state"), extra or {}


def load_params(path: str, template: Optional[Any] = None) -> Any:
    """Serving-plane load: read just the params tree from a checkpoint
    written by :func:`save_model` (or a bare :func:`save` of params),
    WITHOUT requiring ``hvd.init()`` or broadcasting — the model
    registry's hot-swap path (serve/registry.py) loads new weights on
    whatever host runs the roll, and each replica's swap installs the
    same host arrays.  Accepts either layout: a ``{"params": ...,
    "opt_state": ...}`` tree or a params-only tree."""
    restored = _ckptr().restore(os.path.abspath(path), item=template)
    restored = jax.tree_util.tree_map(jax.numpy.asarray, restored)
    if isinstance(restored, dict) and "params" in restored:
        return restored["params"]
    return restored


def restore(path: str, template: Optional[Any] = None,
            broadcast: bool = True) -> Any:
    """Load on rank 0 and broadcast to every rank (broadcast_variables
    pattern).  ``template`` provides the pytree structure/dtypes.  With a
    shared filesystem every rank may read directly (broadcast=False)."""
    topo = _core._require_init().topology
    if topo.size == 1 or topo.emulated or not broadcast:
        restored = _ckptr().restore(os.path.abspath(path), item=template)
        return jax.tree_util.tree_map(jax.numpy.asarray, restored)
    if _core.rank() == 0:
        restored = _ckptr().restore(os.path.abspath(path), item=template)
    else:
        if template is None:
            raise ValueError(
                "restore with broadcast=True needs a template pytree on "
                "non-root ranks (shapes/dtypes for the broadcast)")
        restored = jax.tree_util.tree_map(jax.numpy.zeros_like, template)
    restored = jax.tree_util.tree_map(jax.numpy.asarray, restored)
    return _functions.broadcast_variables(restored, root_rank=0)
