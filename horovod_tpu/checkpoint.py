"""Checkpoint helpers: the rank-0-writes / broadcast-on-load convention.

Reference behavior (SURVEY.md §5.4): Horovod standardizes (a)
broadcast_variables / broadcast_object so rank 0's restored checkpoint
reaches all ranks, (b) "only rank 0 writes to disk" in every example.  This
module packages that convention over orbax (the JAX checkpointing library):
``save`` writes from rank 0 only; ``restore`` loads on rank 0 and
broadcasts, so a freshly-resized elastic world restores consistently.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from . import core as _core
from . import functions as _functions


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save(path: str, state: Any, force: bool = True) -> None:
    """Write ``state`` (pytree) from rank 0 only; other ranks no-op and
    wait at a barrier so nobody races ahead of an incomplete write."""
    from . import ops as _ops
    if _core.rank() == 0:
        _ckptr().save(os.path.abspath(path), jax.device_get(state),
                      force=force)
    if _core.size() > 1 and not _core._require_init().topology.emulated:
        _ops.barrier()


def restore(path: str, template: Optional[Any] = None,
            broadcast: bool = True) -> Any:
    """Load on rank 0 and broadcast to every rank (broadcast_variables
    pattern).  ``template`` provides the pytree structure/dtypes.  With a
    shared filesystem every rank may read directly (broadcast=False)."""
    topo = _core._require_init().topology
    if topo.size == 1 or topo.emulated or not broadcast:
        restored = _ckptr().restore(os.path.abspath(path), item=template)
        return jax.tree_util.tree_map(jax.numpy.asarray, restored)
    if _core.rank() == 0:
        restored = _ckptr().restore(os.path.abspath(path), item=template)
    else:
        if template is None:
            raise ValueError(
                "restore with broadcast=True needs a template pytree on "
                "non-root ranks (shapes/dtypes for the broadcast)")
        restored = jax.tree_util.tree_map(jax.numpy.zeros_like, template)
    restored = jax.tree_util.tree_map(jax.numpy.asarray, restored)
    return _functions.broadcast_variables(restored, root_rank=0)
