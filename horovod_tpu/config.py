"""Typed configuration / knob system.

The reference exposes every tunable through three equivalent layers that all
resolve to ``HOROVOD_*`` environment variables (knob names in
horovod/common/common.h:116-150, read once in BackgroundThreadLoop,
operations.cc:459-650; CLI flags mapped by runner/launch.py:158-243 and the YAML
config file by runner/common/util/config_parser.py).  This module keeps the same
contract: one typed ``Config`` dataclass, populated from the environment with
the reference's knob names (so existing Horovod job scripts keep working), and
override helpers used by the ``horovodrun``-equivalent CLI.

Precedence (same as reference): explicit runtime API > CLI flag (exported as env
by the launcher) > environment > default.

Defaults mirror the reference: fusion threshold 128 MB (operations.cc:519),
cycle time 1 ms (0 under the compiled/XLA path, operations.cc:528-534), response
cache capacity 1024, stall-check warning at 60 s (stall_inspector.h:78).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

# Knob names preserved from the reference (common.h:116-150 and runner/launch.py).
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_TORUS_ALLREDUCE = "HOROVOD_TORUS_ALLREDUCE"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"  # reference: logging.cc:85
HOROVOD_DYNAMIC_PROCESS_SETS = "HOROVOD_DYNAMIC_PROCESS_SETS"
HOROVOD_DISABLE_GROUP_FUSION = "HOROVOD_DISABLE_GROUP_FUSION"
HOROVOD_ELASTIC_TIMEOUT = "HOROVOD_ELASTIC_TIMEOUT"
HOROVOD_GLOO_TIMEOUT_SECONDS = "HOROVOD_GLOO_TIMEOUT_SECONDS"
# Rendezvous / rank env injected by the launcher (runner/gloo_run.py:66-78,
# common/gloo/gloo_context.h:28-42).
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
# TPU-build specific knobs (new; no reference analog).
HVD_TPU_EMULATE_RANKS = "HVD_TPU_EMULATE_RANKS"  # treat N local devices as N ranks
HVD_TPU_MESH_AXIS = "HVD_TPU_MESH_AXIS"          # mesh axis name, default "hvd"
HVD_TPU_COMPILATION_CACHE = "HVD_TPU_COMPILATION_CACHE"  # persistent XLA cache dir
HOROVOD_AUTOTUNE_SEARCH = "HOROVOD_AUTOTUNE_SEARCH"      # 'sweep' | 'bayes'
HOROVOD_AUTOTUNE_BAYES_ROUNDS = "HOROVOD_AUTOTUNE_BAYES_ROUNDS"


def env_bool(name: str, default: bool = False) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def env_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return int(val)
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return float(val)
    except ValueError:
        return default


@dataclasses.dataclass
class Config:
    """All runtime knobs, resolved once at ``init()`` time."""

    # Fusion / cycle (operations.cc:519, :528-534).
    fusion_threshold_bytes: int = 128 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    # Topology-shaped reduction modes. On TPU these select ICI-native layouts
    # rather than separate software algorithms (nccl_operations.h:231,253).
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    torus_allreduce: bool = False
    # Autotune (parameter_manager.h:42-110).
    autotune: bool = False
    autotune_log: Optional[str] = None
    autotune_search: str = "sweep"   # 'bayes' = GP + expected improvement
    autotune_bayes_rounds: int = 12
    # Timeline (timeline.h:48,108).
    timeline_path: Optional[str] = None
    timeline_mark_cycles: bool = False
    # Stall inspector (stall_inspector.h:30,78).
    stall_check_enabled: bool = True
    stall_warning_time_seconds: float = 60.0
    stall_shutdown_time_seconds: float = 0.0
    # Process sets (process_set.h:89).
    dynamic_process_sets: bool = False
    disable_group_fusion: bool = False
    # Elastic.
    elastic_timeout_seconds: float = 600.0
    # Logging.
    log_level: str = "warning"
    log_hide_timestamp: bool = False
    # TPU-specific.
    emulate_ranks: int = 0
    mesh_axis: str = "hvd"
    compilation_cache_dir: Optional[str] = None

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            fusion_threshold_bytes=env_int(
                HOROVOD_FUSION_THRESHOLD, 128 * 1024 * 1024),
            cycle_time_ms=env_float(HOROVOD_CYCLE_TIME, 1.0),
            cache_capacity=env_int(HOROVOD_CACHE_CAPACITY, 1024),
            hierarchical_allreduce=env_bool(HOROVOD_HIERARCHICAL_ALLREDUCE),
            hierarchical_allgather=env_bool(HOROVOD_HIERARCHICAL_ALLGATHER),
            torus_allreduce=env_bool(HOROVOD_TORUS_ALLREDUCE),
            autotune=env_bool(HOROVOD_AUTOTUNE),
            autotune_log=os.environ.get(HOROVOD_AUTOTUNE_LOG),
            autotune_search=os.environ.get(HOROVOD_AUTOTUNE_SEARCH, "sweep"),
            autotune_bayes_rounds=env_int(HOROVOD_AUTOTUNE_BAYES_ROUNDS, 12),
            timeline_path=os.environ.get(HOROVOD_TIMELINE),
            timeline_mark_cycles=env_bool(HOROVOD_TIMELINE_MARK_CYCLES),
            stall_check_enabled=not env_bool(HOROVOD_STALL_CHECK_DISABLE),
            stall_warning_time_seconds=env_float(
                HOROVOD_STALL_CHECK_TIME_SECONDS, 60.0),
            stall_shutdown_time_seconds=env_float(
                HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0),
            dynamic_process_sets=env_bool(HOROVOD_DYNAMIC_PROCESS_SETS),
            disable_group_fusion=env_bool(HOROVOD_DISABLE_GROUP_FUSION),
            elastic_timeout_seconds=env_float(HOROVOD_ELASTIC_TIMEOUT, 600.0),
            log_level=os.environ.get(HOROVOD_LOG_LEVEL, "warning"),
            log_hide_timestamp=env_bool(HOROVOD_LOG_HIDE_TIME),
            emulate_ranks=env_int(HVD_TPU_EMULATE_RANKS, 0),
            mesh_axis=os.environ.get(HVD_TPU_MESH_AXIS, "hvd"),
            compilation_cache_dir=os.environ.get(HVD_TPU_COMPILATION_CACHE),
        )
