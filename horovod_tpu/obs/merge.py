"""Fleet-wide trace aggregation: merge per-process/per-component trace
shards into one Perfetto-openable Chrome trace + a per-request
critical-path summary.

Shards (obs/tracing.py) are JSONL files of span/instant/flow records in
each process's OWN monotonic clock, headed by an anchor record pairing
``time.time_ns()`` with ``time.monotonic_ns()`` at shard open.  The
merge maps every event onto one wall-clock axis:

    wall(ev) = ev.t_ns - anchor.mono_ns + anchor.wall_ns

so per-shard monotonic bases (process start times) drop out; the
residual error between HOSTS is their wall-clock skew, which the
optional rendezvous-KV anchors (tracing.publish_clock_anchor) bound by
the measured KV round-trip time — the merge records that bound per shard
in the output metadata instead of pretending alignment is exact.  After
alignment a parent/child clamp enforces the invariant a human reads the
tree by: a child span never begins before its parent (sub-RTT skew
otherwise draws causality backwards).

The critical-path summary answers ROADMAP item 4's question — where did
this request's latency go? — as queue vs prefill vs decode vs retry time
per trace, with the replicas it crossed and its KV-retry count.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Tuple

#: Span names that aggregate into each critical-path stage.
STAGE_SPANS = {
    "queue": ("queue-wait",),
    "prefill": ("prefill", "prefill-chunk"),
    "decode": ("decode",),
    "retry": ("resubmission", "kv-retry"),
}


class Shard:
    """One loaded shard: its anchor + events, clock-aligned lazily."""

    def __init__(self, path: str, anchor: Optional[dict],
                 events: List[dict]):
        self.path = path
        self.anchor = anchor
        self.events = events
        self.rtt_ns: Optional[int] = None  # KV-refined skew bound

    @property
    def label(self) -> str:
        if self.anchor is not None:
            return str(self.anchor.get("label", "?"))
        return os.path.basename(self.path)

    def wall_ns(self, t_ns: int) -> int:
        """Monotonic → wall (module doc); identity with offset 0 when the
        shard carries no anchor (flagged in the merge metadata)."""
        if self.anchor is None:
            return int(t_ns)
        return int(t_ns - self.anchor["mono_ns"] + self.anchor["wall_ns"])


def load_shards(trace_dir: str) -> List[Shard]:
    """Every ``trace-*.jsonl`` under ``trace_dir``, anchors split out."""
    shards = []
    for path in sorted(glob.glob(os.path.join(trace_dir,
                                              "trace-*.jsonl"))):
        anchor, events = None, []
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail write (killed process)
                if rec.get("type") == "anchor":
                    if anchor is None:
                        anchor = rec
                else:
                    events.append(rec)
        shards.append(Shard(path, anchor, events))
    return shards


def _anchor_proc(a: dict):
    """Host-qualified process identity of an anchor (``proc``; older
    anchors fall back to the bare pid — unique only single-host)."""
    return a.get("proc", a.get("pid"))


def kv_anchors(kv_client) -> Dict[object, dict]:
    """Clock anchors published through the rendezvous KV
    (tracing.publish_clock_anchor), keyed by host-qualified process
    tag — the RTT-bounded refinement source for shards whose processes
    published one.  A bare pid key would collide across hosts
    (containerized replicas are routinely all pid 1)."""
    from .tracing import CLOCK_SCOPE
    out: Dict[object, dict] = {}
    for _, raw in kv_client.scan(CLOCK_SCOPE).items():
        try:
            a = json.loads(raw)
            out[_anchor_proc(a)] = a
        except (ValueError, KeyError, TypeError):
            continue
    return out


def apply_kv_anchors(shards: List[Shard],
                     anchors: Dict[object, dict]) -> None:
    """Attach the KV skew bound (and backfill missing anchors) from the
    rendezvous-KV exchange, matched on host-qualified process tags."""
    for s in shards:
        proc = (_anchor_proc(s.anchor) if s.anchor is not None
                else None)
        a = anchors.get(proc) if proc is not None else None
        if a is None and s.anchor is None and len(anchors) == 1:
            a = next(iter(anchors.values()))
        if a is not None:
            if s.anchor is None:
                s.anchor = a
            s.rtt_ns = a.get("rtt_ns")


def spans_by_trace(shards: List[Shard]) -> Dict[str, List[dict]]:
    """All events grouped by trace id, each stamped with aligned wall
    times (``wall0_ns``/``wall1_ns`` for spans, ``wall_ns`` for points)
    and its shard label."""
    traces: Dict[str, List[dict]] = {}
    for s in shards:
        for ev in s.events:
            ev = dict(ev, shard=s.label)
            if ev["type"] == "span":
                ev["wall0_ns"] = s.wall_ns(ev["t0_ns"])
                ev["wall1_ns"] = s.wall_ns(ev["t1_ns"])
            else:
                ev["wall_ns"] = s.wall_ns(ev["t_ns"])
            traces.setdefault(ev["trace"], []).append(ev)
    return traces


def build_tree(spans: List[dict]) -> List[dict]:
    """Span list → forest of {span, children} nodes.  Parent ids that
    resolve nowhere (upstream hop not captured locally) root their
    subtree.  When aligned wall times exist, children are clamped to
    start no earlier than their parent (module doc)."""
    nodes = {s["span"]: dict(s, children=[]) for s in spans}
    roots = []
    for sid, node in nodes.items():
        parent = nodes.get(node.get("parent"))
        if parent is None:
            roots.append(node)
        else:
            parent["children"].append(node)

    def clamp(node, floor_ns):
        if "wall0_ns" in node and floor_ns is not None:
            if node["wall0_ns"] < floor_ns:
                shift = floor_ns - node["wall0_ns"]
                node["wall0_ns"] += shift
                node["wall1_ns"] += shift
                node["clock_clamped_ns"] = shift
        here = node.get("wall0_ns", floor_ns)
        for c in node["children"]:
            clamp(c, here)

    for r in roots:
        clamp(r, None)
        _sort_children(r)
    roots.sort(key=_node_ts)
    return roots


def _node_ts(n: dict) -> int:
    # Aligned wall time when the merge stamped it; raw monotonic stamp
    # for single-process trees (the /trace endpoint's recent buffer).
    return n.get("wall0_ns",
                 n.get("wall_ns", n.get("t0_ns", n.get("t_ns", 0))))


def _sort_children(node: dict) -> None:
    node["children"].sort(key=_node_ts)
    for c in node["children"]:
        _sort_children(c)


def local_roots(spans: List[dict]) -> List[dict]:
    """Spans whose parent resolves to no LOCAL span — the tree roots.
    A trace continued from an upstream hop (inbound ``X-Parent-Span``)
    has a root whose parent id names a span the upstream service holds:
    still a root here (the same rule ``build_tree`` applies)."""
    ids = {s["span"] for s in spans}
    return [s for s in spans
            if s.get("parent") is None or s["parent"] not in ids]


def critical_path(events: List[dict]) -> dict:
    """One trace's latency decomposition (module doc): per-stage
    milliseconds from its spans, total from the root span, plus the
    replicas the request crossed and its retry/resubmission counts."""
    spans = [e for e in events if e["type"] == "span"]
    roots = local_roots(spans)
    # Prefer the designated request root over orphaned children (a
    # child can arrive in a shard whose root went to another shard).
    roots.sort(key=lambda s: (s["name"] not in ("http-handle",
                                                "request"),
                              s["t0_ns"]))
    root = roots[0] if roots else None
    by_stage = {k: 0.0 for k in STAGE_SPANS}
    counts = {"kv_retries": 0, "resubmissions": 0, "prefill_chunks": 0}
    replicas = set()
    for s in spans:
        dur_ms = (s["t1_ns"] - s["t0_ns"]) / 1e6
        for stage, names in STAGE_SPANS.items():
            if s["name"] in names:
                by_stage[stage] += dur_ms
        if s["name"] == "kv-retry":
            counts["kv_retries"] += 1
        elif s["name"] == "resubmission":
            counts["resubmissions"] += 1
        elif s["name"] == "prefill-chunk":
            counts["prefill_chunks"] += 1
        proc = s.get("proc", "")
        if proc not in ("server", "kv-client") and proc:
            replicas.add(proc)
    if root is not None and root["name"] in ("http-handle", "request"):
        total_ms = (root["t1_ns"] - root["t0_ns"]) / 1e6
    elif spans:
        # No designated request root captured (partial shard set):
        # total = the spans' overall envelope, not a lossy stage sum —
        # on the ALIGNED axis when the merge stamped one (raw monotonic
        # stamps from different processes do not share a zero).
        total_ms = (max(s.get("wall1_ns", s["t1_ns"]) for s in spans)
                    - min(s.get("wall0_ns", s["t0_ns"])
                          for s in spans)) / 1e6
    else:
        total_ms = sum(by_stage.values())
    return {
        "total_ms": round(total_ms, 3),
        "stages_ms": {k: round(v, 3) for k, v in by_stage.items()},
        "replicas": sorted(replicas),
        "root": root["name"] if root is not None else None,
        **counts,
    }


def merge_chrome(shards: List[Shard]) -> Tuple[List[dict], dict]:
    """Shards → (Chrome-trace event array, merge metadata).

    Spans render as async begin/end pairs keyed by trace id, flows as
    s/t/f, instants as i — the same rendering the in-process Timeline
    uses, so a merged fleet trace reads identically to a single-process
    one.  Events are globally time-sorted: the output's ``ts`` axis is
    monotonic by construction.
    """
    labels = sorted({s.label for s in shards})
    pid_of = {label: i for i, label in enumerate(labels)}
    base_ns = None
    for s in shards:
        for ev in s.events:
            t = s.wall_ns(ev.get("t0_ns", ev.get("t_ns", 0)))
            base_ns = t if base_ns is None else min(base_ns, t)
    base_ns = base_ns or 0

    def us(wall_ns: int) -> float:
        return (wall_ns - base_ns) / 1e3

    out: List[dict] = []
    for label in labels:
        out.append({"name": "process_name", "ph": "M",
                    "pid": pid_of[label], "args": {"name": label}})
    timed: List[dict] = []
    for s in shards:
        pid = pid_of[s.label]
        for ev in s.events:
            if ev["type"] == "span":
                base = {"cat": "hvdtrace", "id": ev["trace"],
                        "name": ev["name"], "pid": pid,
                        "tid": ev["trace"][:8]}
                args = dict(ev.get("args", {}), span=ev["span"],
                            parent=ev.get("parent"), shard=s.label)
                timed.append(dict(base, ph="b",
                                  ts=us(s.wall_ns(ev["t0_ns"])),
                                  args=args))
                timed.append(dict(base, ph="e",
                                  ts=us(s.wall_ns(ev["t1_ns"]))))
            elif ev["type"] == "flow":
                rec = {"cat": "hvdtrace-flow", "id": ev["trace"],
                       "name": ev["name"], "ph": ev["phase"],
                       "ts": us(s.wall_ns(ev["t_ns"])), "pid": pid,
                       "tid": ev["trace"][:8]}
                if ev["phase"] == "f":
                    rec["bp"] = "e"
                timed.append(rec)
            else:  # instant
                timed.append({
                    "name": f"hvdtrace/{ev['name']}", "ph": "i", "s": "p",
                    "ts": us(s.wall_ns(ev["t_ns"])), "pid": pid,
                    "tid": ev["trace"][:8],
                    "args": dict(ev.get("args", {}),
                                 trace_id=ev["trace"])})
    timed.sort(key=lambda e: (e["ts"], 0 if e.get("ph") != "e" else 1))
    meta = {
        "shards": [{
            "label": s.label, "path": os.path.basename(s.path),
            "events": len(s.events), "anchored": s.anchor is not None,
            "skew_bound_ns": s.rtt_ns,
        } for s in shards],
        "traces": len({e["trace"] for s in shards for e in s.events}),
    }
    return out + timed, meta


def load_timeline_events(path: str) -> List[dict]:
    """In-process ``Timeline`` chrome-trace array (timeline.py) → event
    list.  Tolerates an unterminated array (killed process: the writer
    thread never wrote the closing bracket) by falling back to
    line-wise parsing — the same torn-tail discipline ``load_shards``
    applies to JSONL shards."""
    with open(path) as fh:
        text = fh.read()
    try:
        evs = json.loads(text)
    except ValueError:
        evs = []
        for line in text.splitlines():
            line = line.strip().rstrip(",")
            if not line or line in ("[", "]"):
                continue
            try:
                evs.append(json.loads(line))
            except ValueError:
                continue  # torn tail write
    return [e for e in evs if isinstance(e, dict)]


def append_timelines(events: List[dict], meta: dict,
                     paths: List[str]) -> Tuple[List[dict], dict]:
    """Fold in-process Timeline files (COLLECTIVE/MEMORY/COMM_CENSUS
    counters, ELASTIC instants, op lifecycle) into a merged fleet trace
    under their own pids.  Timelines carry no wall-clock anchor (their
    ``ts`` axis is µs since Timeline open), so events keep their own
    time base — counters and instants read fine in Perfetto per
    process, and the metadata says which pids are unaligned rather than
    pretending they share the request-span axis."""
    used = {e.get("pid") for e in events if isinstance(e.get("pid"), int)}
    next_pid = (max(used) + 1) if used else 0
    meta = dict(meta, timelines=[])
    for path in paths:
        tl_events = load_timeline_events(path)
        label = f"timeline:{os.path.basename(path)}"
        events.append({"name": "process_name", "ph": "M",
                       "pid": next_pid, "args": {"name": label}})
        for ev in tl_events:
            if ev.get("ph") == "M":
                continue  # one process_name per file, assigned above
            events.append(dict(ev, pid=next_pid))
        meta["timelines"].append({
            "label": label, "path": os.path.basename(path),
            "events": len(tl_events), "pid": next_pid,
            "aligned": False,
        })
        next_pid += 1
    return events, meta


def summarize(shards: List[Shard]) -> Dict[str, dict]:
    """Per-trace critical-path summaries keyed by trace id."""
    return {tid: critical_path(evs)
            for tid, evs in spans_by_trace(shards).items()}
