"""horovod_tpu.obs — end-to-end distributed request tracing and
fleet-wide trace aggregation (``hvdtrace``).

The Horovod Timeline (timeline.py) answers "what was each rank doing";
this subsystem answers "where did THIS request's latency go" across the
serve fleet PRs 3-7 built — http-handle → route → queue-wait → prefill
chunk(s) → per-iteration decode flow → KV retries → failover
resubmission — in the Dapper/OpenTelemetry mold, rendered into the same
Chrome-trace machinery so request spans, training-op lifecycles,
FAULTLINE instants, and SERVE counters share one Perfetto view.

Layers (docs/observability.md has the walkthrough):

* :mod:`tracing` — TraceContext + contextvar propagation, the sampled
  process-global :class:`~tracing.Tracer` (``HVD_TRACE_SAMPLE``, zero
  hot-path cost when off), per-component JSONL trace shards
  (``HVD_TRACE_DIR``), wire propagation via ``X-Trace-Id`` /
  ``X-Parent-Span``;
* :mod:`merge` — shard loading, wall-clock alignment with rendezvous-KV
  RTT skew bounds, span-tree building, per-request critical paths;
* :mod:`cli`  — the ``hvdtrace`` console entry
  (``python -m horovod_tpu.obs``).

Quickstart::

    HVD_TRACE_SAMPLE=0.05 HVD_TRACE_DIR=/tmp/hvdtrace hvdserve ...
    hvdtrace --dir /tmp/hvdtrace -o fleet.json   # open in Perfetto
"""

# NOTE: the live tracer global is ``tracing.TRACER`` — deliberately NOT
# re-exported here: ``from .tracing import TRACER`` would bind an
# import-time snapshot (None) that install() never rebinds, silently
# disabling any consumer that guarded on it.  Check ``tracing.TRACER``
# (or call ``active_tracer()``) instead.
from .tracing import (  # noqa: F401
    CLOCK_SCOPE, TraceContext, Tracer, active_tracer, clock_anchor,
    current, current_trace_id, install, maybe_install_from_env, pop,
    publish_clock_anchor, push, scope, uninstall,
)
from .merge import (  # noqa: F401
    Shard, build_tree, critical_path, kv_anchors, load_shards,
    merge_chrome, spans_by_trace, summarize,
)
from .cli import run_commandline  # noqa: F401
