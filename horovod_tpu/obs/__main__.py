import sys

from .cli import run_commandline

if __name__ == "__main__":
    sys.exit(run_commandline())
