"""``hvdtrace`` — merge fleet trace shards and print the per-request
critical-path summary.

::

    hvdtrace --dir /tmp/hvdtrace -o fleet-trace.json
    python -m horovod_tpu.obs --dir /tmp/hvdtrace --kv host:port

Exit contract: 0 merged, 1 no shards found / unreadable dir, 2 usage
(argparse).  The merged file is a Chrome-trace JSON array openable in
Perfetto / chrome://tracing; the summary prints one line per request
(queue / prefill / decode / spec / retry milliseconds, replicas
crossed, retry counts) — the latency decomposition ROADMAP item 4's autoscaler
consumes in histogram form from ``/metrics``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _fmt_summary(trace_id: str, cp: dict) -> str:
    st = cp["stages_ms"]
    extras = []
    if cp["resubmissions"]:
        extras.append(f"resubmits={cp['resubmissions']}")
    if cp["kv_retries"]:
        extras.append(f"kv_retries={cp['kv_retries']}")
    return (f"{trace_id}  total={cp['total_ms']:9.2f}ms  "
            f"queue={st['queue']:8.2f}  prefill={st['prefill']:8.2f}  "
            f"decode={st['decode']:8.2f}  retry={st['retry']:8.2f}  "
            f"replicas={','.join(cp['replicas']) or '-'}"
            + ("  " + " ".join(extras) if extras else ""))


def run_commandline(argv: Optional[list] = None) -> int:
    from . import merge as _merge

    parser = argparse.ArgumentParser(
        prog="hvdtrace",
        description="Merge hvdtrace shards (HVD_TRACE_DIR) from every "
                    "rank/replica into one Perfetto-openable Chrome "
                    "trace with clock-offset alignment, and print the "
                    "per-request critical-path summary "
                    "(docs/observability.md)")
    parser.add_argument("--dir", "-d", default=os.environ.get(
        "HVD_TRACE_DIR", "."), help="shard directory (default: "
        "HVD_TRACE_DIR or the current directory)")
    parser.add_argument("--out", "-o", default=None,
                        help="merged Chrome-trace JSON output path "
                             "(omit to only print the summary)")
    parser.add_argument("--kv", default=None, metavar="ADDR:PORT",
                        help="rendezvous KV to read clock anchors from "
                             "(tracing.publish_clock_anchor) — refines "
                             "shard alignment and records the RTT skew "
                             "bound")
    parser.add_argument("--json", action="store_true",
                        help="print the summary as JSON instead of text")
    parser.add_argument("--timeline", action="append", default=[],
                        metavar="FILE",
                        help="also fold an in-process Timeline chrome "
                             "trace (horovod_tpu.timeline) into the "
                             "merged output — COLLECTIVE/MEMORY/"
                             "COMM_CENSUS counters and ELASTIC instants "
                             "land next to the request spans under "
                             "their own pid (repeatable; no cross-clock "
                             "alignment: timelines carry no wall anchor)")
    args = parser.parse_args(argv)

    if not os.path.isdir(args.dir):
        print(f"hvdtrace: no such directory: {args.dir}", file=sys.stderr)
        return 1
    shards = _merge.load_shards(args.dir)
    if not shards:
        print(f"hvdtrace: no trace-*.jsonl shards under {args.dir} "
              f"(set HVD_TRACE_DIR on the serving processes)",
              file=sys.stderr)
        return 1
    if args.kv:
        try:
            addr, port = args.kv.rsplit(":", 1)
            from ..runner.http_server import KVStoreClient
            _merge.apply_kv_anchors(
                shards, _merge.kv_anchors(KVStoreClient(addr, int(port))))
        except Exception as e:
            print(f"hvdtrace: KV anchor read failed ({e}); falling back "
                  f"to shard anchors", file=sys.stderr)

    events, meta = _merge.merge_chrome(shards)
    for path in args.timeline:
        if not os.path.isfile(path):
            print(f"hvdtrace: no such timeline file: {path}",
                  file=sys.stderr)
            return 1
    if args.timeline:
        events, meta = _merge.append_timelines(events, meta,
                                               args.timeline)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(events, fh)
        print(f"hvdtrace: wrote {len(events)} events from "
              f"{len(shards)} shard(s) ({meta['traces']} trace(s)) to "
              f"{args.out}")
    summary = _merge.summarize(shards)
    if args.json:
        print(json.dumps({"meta": meta, "traces": summary}, indent=2))
    else:
        for tid in sorted(summary,
                          key=lambda t: -summary[t]["total_ms"]):
            print(_fmt_summary(tid, summary[tid]))
        skews = [s["skew_bound_ns"] for s in meta["shards"]
                 if s["skew_bound_ns"] is not None]
        if skews:
            print(f"# clock skew bound (KV RTT): "
                  f"{max(skews) / 1e6:.3f} ms across "
                  f"{len(skews)} anchored shard(s)")
    return 0
