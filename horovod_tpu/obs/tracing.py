"""Distributed request tracing in the Dapper/OpenTelemetry mold.

The Horovod Timeline (timeline.py) is per-process and op-centric: it
shows WHAT each rank was doing, but a serving request that crosses the
HTTP front-end, the router, a replica's batcher, chunked prefill, the
decode loop, KV-transport retries, tier-fault stalls (the ``tier-fault``
span hvdtier emits when a host/fleet KV fetch loses its prefetch race,
serve/tiering.py), and possibly a failover resubmission leaves no single
artifact saying where ITS latency went.  This module adds the
per-request plane:

* a :class:`TraceContext` (trace_id, span_id, parent) carried in a
  ``contextvars.ContextVar`` on the thread doing request work and ON the
  request object across thread handoffs (HTTP handler → batcher queue →
  engine loop), propagated over the wire via ``X-Trace-Id`` /
  ``X-Parent-Span`` headers (serve/server.py inbound+echo, the runner KV
  client outbound);
* a process-global :class:`Tracer` (``TRACER``) that records spans
  retroactively — callers capture ``time.monotonic()`` marks where work
  happens and emit the whole span at its end — into (a) per-component
  JSONL *trace shards* under ``HVD_TRACE_DIR`` for fleet-wide merging
  (obs/merge.py, the ``hvdtrace`` CLI), (b) the ambient Timeline as
  Chrome async/flow events so request spans interleave with the
  training-op lifecycle, FAULTLINE instants, and SERVE counters in one
  Perfetto view, and (c) a bounded recent-trace buffer the sampled
  ``/trace`` endpoint serves as JSON span trees;
* sampling via ``HVD_TRACE_SAMPLE`` (probability a new root request is
  traced; while the tracer is installed — any sample > 0 — an incoming
  ``X-Trace-Id`` header bypasses the local roll, because the upstream
  hop made the sampling decision).  Off by default with zero hot-path
  cost: the guard every instrumented path uses is ``tracing.TRACER is
  not None`` — one module-attribute read, matching faultline's
  discipline.  With the tracer off, inbound trace ids are only ECHOED
  (correlation survives the untraced hop), never traced.

Clock alignment for the fleet merge: every shard opens with an anchor
record pairing ``time.time_ns()`` with ``time.monotonic_ns()``, and
:func:`publish_clock_anchor` additionally publishes the anchor through
the rendezvous KV with the measured put round-trip time — the merger
aligns shards on the wall-clock anchors and bounds the residual
cross-host skew by the KV RTT (docs/observability.md).
"""

from __future__ import annotations

import contextvars
import json
import os
import queue
import random
import socket
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

#: KV scope clock anchors are exchanged through (publish_clock_anchor /
#: merge.kv_anchors).
CLOCK_SCOPE = "hvdtrace-clock"

#: The active tracer, or None (the default — instrumented paths no-op
#: behind a single attribute read).
TRACER: Optional["Tracer"] = None

_env_lock = threading.Lock()
_env_checked = False

_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("hvdtrace_ctx", default=None)

_id_rng = random.Random()
_id_lock = threading.Lock()


def _gen_id(nibbles: int) -> str:
    with _id_lock:
        return "%0*x" % (nibbles, _id_rng.getrandbits(nibbles * 4))


def _proc_tag() -> str:
    """Host-qualified process identity for shard filenames and KV
    anchor keys.  A bare pid is NOT unique across hosts (containerized
    replicas are routinely all pid 1): two hosts sharing an
    HVD_TRACE_DIR would append to the same shard and wall-align each
    other's events with the wrong clock anchor."""
    host = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in socket.gethostname()) or "host"
    return f"{host}-{os.getpid()}"


class TraceContext:
    """One request's identity at one point in the span tree: the
    trace_id names the request end-to-end, span_id this hop's span, and
    parent_id the upstream hop's span (None at the root)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def headers(self) -> List[Tuple[str, str]]:
        """Wire form: what a downstream hop receives (its parent is THIS
        hop's span)."""
        return [("X-Trace-Id", self.trace_id),
                ("X-Parent-Span", self.span_id)]

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_id})")


def current() -> Optional[TraceContext]:
    """The thread/task's active trace context (None untraced)."""
    return _current.get()


def current_trace_id() -> Optional[str]:
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


def push(ctx: Optional[TraceContext]):
    """Set the active context; returns the token for :func:`pop`."""
    return _current.set(ctx)


def pop(token) -> None:
    _current.reset(token)


class scope:
    """``with tracing.scope(ctx): ...`` — context-manager form of
    push/pop for code that does request work on its own thread."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


class Tracer:
    """Process-global span recorder (module doc).

    ``sample`` is the probability a NEW root request is traced;
    ``shard_dir`` (``HVD_TRACE_DIR``) enables per-component JSONL shard
    files for the fleet merge; ``recent`` bounds the in-memory buffer
    the ``/trace`` endpoint reads.  All sinks are best-effort: tracing
    must never take down the serving path.
    """

    def __init__(self, sample: float = 0.0,
                 shard_dir: Optional[str] = None,
                 recent: Optional[int] = None,
                 rank: Optional[int] = None):
        self.sample = max(float(sample), 0.0)
        self.shard_dir = shard_dir or None
        self.rank = int(rank) if rank is not None else 0
        self._recent_cap = recent if recent is not None else int(
            os.environ.get("HVD_TRACE_RECENT", "128"))
        self._lock = threading.Lock()
        self._rng = random.Random()
        # trace_id -> list of event records, insertion-ordered so the
        # buffer evicts the OLDEST trace when past the cap.
        self._traces: "OrderedDict[str, List[dict]]" = OrderedDict()
        self._flow_state: Dict[str, bool] = {}  # trace_id -> flow started
        self._timeline = None
        self._closed = False
        self.spans_emitted = 0
        # Shard IO runs on a dedicated writer thread behind a BOUNDED
        # queue (the timeline.py discipline): request-path threads —
        # engine loops, HTTP handlers, KV clients — must never sit on a
        # disk write inside the tracer lock.  Past the cap, records
        # DROP and are counted (spans_dropped); the in-memory recent
        # buffer and the timeline sink are unaffected.
        self.spans_dropped = 0
        self._wq: "queue.Queue[Optional[Tuple[str, str]]]" = queue.Queue(
            maxsize=8192)
        self._writer_thread: Optional[threading.Thread] = None
        self._writers: Dict[str, object] = {}  # writer-thread only

    # -- wiring ---------------------------------------------------------------

    def set_timeline(self, timeline) -> None:
        """Register a ``timeline.Timeline``; spans additionally render as
        Chrome async/flow events in the in-process trace."""
        self._timeline = timeline

    # -- sampling / context ---------------------------------------------------

    def should_sample(self) -> bool:
        if self.sample <= 0.0:
            return False
        if self.sample >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < self.sample

    def new_context(self, trace_id: Optional[str] = None,
                    parent: Optional[str] = None) -> TraceContext:
        """A new span context: fresh trace when ``trace_id`` is None,
        continuation of an upstream hop otherwise (``parent`` = the
        upstream span id from ``X-Parent-Span``)."""
        return TraceContext(trace_id or _gen_id(16), _gen_id(8), parent)

    # -- emission -------------------------------------------------------------

    def emit_span(self, ctx: TraceContext, name: str,
                  t0: float, t1: float, component: str,
                  args: Optional[dict] = None, root: bool = False) -> dict:
        """Record one completed span.  ``t0``/``t1`` are
        ``time.monotonic()`` seconds captured where the work happened
        (retroactive emission keeps the hot path to clock reads).  A
        ``root`` span IS ``ctx``'s own span (parent = ctx.parent_id);
        a non-root span becomes a fresh child of ``ctx``."""
        rec = {"type": "span", "trace": ctx.trace_id,
               "span": ctx.span_id if root else _gen_id(8),
               "parent": ctx.parent_id if root else ctx.span_id,
               "name": name, "proc": component,
               "t0_ns": int(t0 * 1e9), "t1_ns": int(max(t1, t0) * 1e9),
               "args": args or {}}
        self._record(component, rec)
        tl = self._timeline
        if tl is not None:
            try:
                tl.trace_span(ctx.trace_id, name, component,
                              rec["t0_ns"],
                              (rec["t1_ns"] - rec["t0_ns"]) / 1e3,
                              args=dict(rec["args"], span=rec["span"],
                                        parent=rec["parent"]))
            except Exception:
                pass  # telemetry must never take down the request path
        return rec

    def instant(self, ctx: TraceContext, name: str, component: str,
                args: Optional[dict] = None,
                t: Optional[float] = None) -> dict:
        """Request-scoped point event (deadline expiry, resubmission,
        preemption)."""
        t = time.monotonic() if t is None else t
        rec = {"type": "instant", "trace": ctx.trace_id,
               "parent": ctx.span_id, "name": name, "proc": component,
               "t_ns": int(t * 1e9), "args": args or {}}
        self._record(component, rec)
        tl = self._timeline
        if tl is not None:
            try:
                tl.trace_instant(ctx.trace_id, name, component,
                                 args=rec["args"], mono_ns=rec["t_ns"])
            except Exception:
                pass
        return rec

    def flow(self, ctx: TraceContext, name: str, component: str,
             end: bool = False) -> None:
        """Per-decode-iteration flow: the first call per trace emits the
        flow START, later calls STEPs, ``end=True`` the FINISH — Perfetto
        draws the token stream as arrows through the request's spans."""
        with self._lock:
            started = self._flow_state.get(ctx.trace_id, False)
            if end:
                self._flow_state.pop(ctx.trace_id, None)
            else:
                self._flow_state[ctx.trace_id] = True
        phase = "f" if end else ("t" if started else "s")
        rec = {"type": "flow", "trace": ctx.trace_id, "name": name,
               "proc": component, "phase": phase,
               "t_ns": time.monotonic_ns()}
        self._record(component, rec)
        tl = self._timeline
        if tl is not None:
            try:
                tl.trace_flow(ctx.trace_id, name, component, phase,
                              mono_ns=rec["t_ns"])
            except Exception:
                pass

    # -- sinks ----------------------------------------------------------------

    def _record(self, component: str, rec: dict) -> None:
        # Serialization outside the lock (pure CPU); the ENQUEUE stays
        # inside the _closed-checked section — a put racing close()
        # past the check would land behind the shutdown sentinel and
        # vanish uncounted.  put_nowait never blocks, so no IO happens
        # under the lock; file writes live on the writer thread.
        line = json.dumps(rec) if self.shard_dir is not None else None
        with self._lock:
            if self._closed:
                return
            self.spans_emitted += 1
            spans = self._traces.get(rec["trace"])
            if spans is None:
                spans = self._traces[rec["trace"]] = []
                while len(self._traces) > self._recent_cap:
                    evicted, _ = self._traces.popitem(last=False)
                    self._flow_state.pop(evicted, None)
            spans.append(rec)
            if line is not None:
                if self._writer_thread is None:
                    self._writer_thread = threading.Thread(
                        target=self._drain_shards, daemon=True,
                        name="hvdtrace-writer")
                    self._writer_thread.start()
                try:
                    self._wq.put_nowait((component, line))
                except queue.Full:
                    # A full queue drops the record (counted) rather
                    # than stalling the request path.
                    self.spans_dropped += 1

    # -- shard writer thread --------------------------------------------------

    def _drain_shards(self) -> None:
        while True:
            item = self._wq.get()
            if item is None:
                return
            component, line = item
            try:
                self._writer(component).write(line + "\n")
            except Exception:
                self.shard_dir = None  # disk trouble: stop shard IO

    def _writer(self, component: str):
        """Per-component shard file, opened lazily (WRITER THREAD only)
        with a clock-anchor header (merge.py aligns shards on it).
        Filenames are host-qualified — a bare pid collides across
        hosts (_proc_tag)."""
        fh = self._writers.get(component)
        if fh is None:
            os.makedirs(self.shard_dir, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "_"
                           for c in component)
            path = os.path.join(
                self.shard_dir, f"trace-{_proc_tag()}-{safe}.jsonl")
            fh = open(path, "a", buffering=1)
            fh.write(json.dumps(clock_anchor(component,
                                             rank=self.rank)) + "\n")
            self._writers[component] = fh
        return fh

    # -- /trace endpoint ------------------------------------------------------

    def recent_traces(self, limit: Optional[int] = None) -> List[dict]:
        """The most recent traces as span trees (newest first) — the
        ``/trace`` endpoint's payload.  ``limit`` defaults to the full
        buffer (``HVD_TRACE_RECENT``) — the knob that sizes what the
        endpoint serves."""
        from .merge import build_tree, local_roots
        limit = self._recent_cap if limit is None else limit
        with self._lock:
            items = list(self._traces.items())[-max(limit, 1):]
        out = []
        for trace_id, recs in reversed(items):
            spans = [r for r in recs if r["type"] == "span"]
            out.append({
                "trace_id": trace_id,
                # A trace continued from upstream roots at a span whose
                # parent lives on the other service — still complete
                # locally once that root span is emitted.
                "complete": bool(local_roots(spans)),
                "events": len(recs),
                "tree": build_tree(spans),
            })
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            writer = self._writer_thread
            self._writer_thread = None
        if writer is not None:
            from ..timeline import force_put_sentinel

            def count_drop():
                with self._lock:
                    self.spans_dropped += 1
            # _closed is set, so no new records enqueue.
            force_put_sentinel(self._wq, count_drop)
            writer.join(timeout=5)
            if writer.is_alive():
                return  # wedged on disk: abandon, daemon dies with us
        writers, self._writers = dict(self._writers), {}
        for fh in writers.values():
            try:
                fh.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# clock anchors
# ---------------------------------------------------------------------------

def clock_anchor(label: str, rank: int = 0) -> dict:
    """A (wall, monotonic) clock pairing for shard alignment, keyed by
    host-qualified process identity (a bare pid collides across
    hosts)."""
    return {"type": "anchor", "label": label, "pid": os.getpid(),
            "proc": _proc_tag(), "rank": int(rank),
            "wall_ns": time.time_ns(), "mono_ns": time.monotonic_ns()}


def publish_clock_anchor(kv_client, label: str, rank: int = 0) -> dict:
    """Publish this process's clock anchor through the rendezvous KV
    (scope ``hvdtrace-clock``) with the measured put round-trip time —
    the merge refines shard alignment with these and reports the RTT as
    the cross-host skew bound (module doc)."""
    anchor = clock_anchor(label, rank=rank)
    key = f"{_proc_tag()}-{label}"
    t0 = time.monotonic_ns()
    kv_client.put(CLOCK_SCOPE, key, json.dumps(anchor).encode())
    anchor["rtt_ns"] = time.monotonic_ns() - t0
    # Second put carries the RTT measurement itself (idempotent key).
    kv_client.put(CLOCK_SCOPE, key, json.dumps(anchor).encode())
    return anchor


# ---------------------------------------------------------------------------
# install / env bootstrap
# ---------------------------------------------------------------------------

def install(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process's active tracer and wire the ambient
    timeline (if one is running) so spans land in the in-process
    Chrome trace too."""
    global TRACER
    try:
        from .. import core as _core
        tl = getattr(_core._state, "timeline", None)
        if tl is not None:
            tracer.set_timeline(tl)
        if _core.is_initialized():
            tracer.rank = _core.rank()
    except Exception:
        pass
    TRACER = tracer
    return tracer


def active_tracer() -> Optional["Tracer"]:
    """The live tracer (None when off).  Importable consumers must read
    through this (or ``tracing.TRACER``) — a ``from ... import TRACER``
    snapshot taken before install() stays None forever."""
    return TRACER


def uninstall() -> None:
    global TRACER
    t = TRACER
    TRACER = None
    if t is not None:
        t.close()


def maybe_install_from_env() -> Optional[Tracer]:
    """One-shot env bootstrap (``HVD_TRACE_SAMPLE`` / ``HVD_TRACE_DIR``),
    constructor-time like faultline's: the env is read when the first
    instrumented subsystem comes up.  Checked once per process; a
    programmatically-installed tracer is never overridden."""
    global _env_checked
    if TRACER is not None:
        return TRACER
    with _env_lock:
        if _env_checked or TRACER is not None:
            return TRACER
        _env_checked = True
        try:
            sample = float(os.environ.get("HVD_TRACE_SAMPLE", "0"))
        except ValueError:
            sample = 0.0
        if sample <= 0.0:
            return None
        return install(Tracer(sample=sample,
                              shard_dir=os.environ.get("HVD_TRACE_DIR")
                              or None))
