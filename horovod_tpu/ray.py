"""horovod_tpu.ray — Ray cluster integration namespace.

Reference surface (horovod/ray/__init__.py): RayExecutor (static worlds,
ray/runner.py:45) and the elastic executor + discovery
(ray/elastic_v2.py).  Both gate on ``import ray`` at call time — the core
framework does not depend on it.
"""

from .ray_integration import RayExecutor  # noqa: F401
from .ray_elastic import (  # noqa: F401
    ElasticRayExecutor, RayHostDiscovery,
)
