"""ctypes bindings for the native control-plane core (libhvdcore.so).

The reference exposes its C++ core through an ``extern "C"`` surface consumed
by ctypes (horovod/common/basics.py:29 HorovodBasics); this module is the
same pattern: build-on-first-import (Makefile, g++), load with ctypes, wrap
in small Python classes.  See csrc/hvd_core.cc for what lives natively and
why.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libhvdcore.so")
_lib = None
_lock = threading.Lock()


# Expected native ABI (hvd_core.cc hvd_core_abi_version): symbol additions
# bump this number.  The library is LOADED through an ABI-tagged filename
# (libhvdcore.abi<N>.so): dlopen caches by pathname, so a process that
# loaded a stale build could never swap it for a rebuilt one under the same
# name — the tagged name guarantees the first (and only) load in a process
# is a build of the expected ABI.  A prebuilt base .so from an older tree
# just means one `make clean` rebuild on first use of the new tree.
_ABI = 2
_SO_TAGGED = os.path.join(_DIR, f"libhvdcore.abi{_ABI}.so")


def _build() -> None:
    """Produce the ABI-tagged library under an exclusive file lock: N
    freshly-launched workers race on first import; exactly one runs make
    (which itself writes via temp + rename), the rest wait and load the
    finished library."""
    import fcntl
    import glob
    import shutil
    lock_path = os.path.join(_DIR, ".build.lock")
    with open(lock_path, "w") as lock_fh:
        fcntl.flock(lock_fh, fcntl.LOCK_EX)
        try:
            if os.path.exists(_SO_TAGGED):
                return  # another worker finished while we waited
            # The base .so may exist from an older tree (make only fires
            # on a missing target): always rebuild it for a new tag.
            subprocess.run(["make", "-s", "-C", _DIR, "clean"],
                           check=True, capture_output=True)
            subprocess.run(["make", "-s", "-C", _DIR], check=True,
                           capture_output=True)
            # Sweep only OTHER tags.  Unlinking the tag being produced
            # opens a window where a reader that already passed its
            # exists() check dlopens a missing path (os.replace below
            # overwrites it atomically, no unlink needed); the ENOENT
            # races left are absorbed by lib()'s one-shot retry.
            for stale in glob.glob(os.path.join(_DIR, "libhvdcore.abi*.so")):
                if os.path.abspath(stale) == _SO_TAGGED:
                    continue
                try:
                    os.remove(stale)
                except OSError:
                    pass  # a concurrent sweep already got it
            tmp = _SO_TAGGED + ".tmp"
            shutil.copy2(_SO, tmp)
            os.replace(tmp, _SO_TAGGED)
        finally:
            fcntl.flock(lock_fh, fcntl.LOCK_UN)


def lib() -> ctypes.CDLL:
    """Load (building if needed) the native core."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_TAGGED):
            _build()
        try:
            l = ctypes.CDLL(_SO_TAGGED)
        except OSError:
            # Lost a race with another process's _build() (an older tree's
            # sweep could unlink the tagged file between our exists()
            # check and dlopen) or found a damaged artifact: force one
            # real rebuild — remove the tag so _build() cannot take its
            # already-exists early return — and retry once.
            try:
                os.remove(_SO_TAGGED)
            except OSError:
                pass
            _build()
            l = ctypes.CDLL(_SO_TAGGED)
        l.hvd_core_abi_version.restype = ctypes.c_int
        if l.hvd_core_abi_version() != _ABI:
            raise RuntimeError(
                f"{_SO_TAGGED} reports ABI {l.hvd_core_abi_version()}, "
                f"expected {_ABI}; delete horovod_tpu/csrc/libhvdcore*.so "
                f"and re-import to rebuild")
        # Signatures.
        sig_args = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
                    ctypes.c_int, ctypes.c_double, ctypes.c_double,
                    ctypes.c_int]
        l.hvd_cache_create.restype = ctypes.c_void_p
        l.hvd_cache_create.argtypes = [ctypes.c_int64]
        l.hvd_cache_destroy.argtypes = [ctypes.c_void_p]
        l.hvd_cache_lookup.restype = ctypes.c_int
        l.hvd_cache_lookup.argtypes = sig_args
        l.hvd_cache_put.restype = ctypes.c_int64
        l.hvd_cache_put.argtypes = sig_args
        l.hvd_cache_invalidate.restype = ctypes.c_int
        l.hvd_cache_invalidate.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_cache_clear.argtypes = [ctypes.c_void_p]
        l.hvd_cache_size.restype = ctypes.c_int64
        l.hvd_cache_size.argtypes = [ctypes.c_void_p]

        l.hvd_msgtable_create.restype = ctypes.c_void_p
        l.hvd_msgtable_create.argtypes = [ctypes.c_int]
        l.hvd_msgtable_destroy.argtypes = [ctypes.c_void_p]
        l.hvd_msgtable_set_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
        l.hvd_msgtable_increment.restype = ctypes.c_int
        l.hvd_msgtable_increment.argtypes = sig_args + [ctypes.c_int]
        l.hvd_msgtable_validate.restype = ctypes.c_char_p
        l.hvd_msgtable_validate.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_msgtable_erase.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_msgtable_pending.restype = ctypes.c_char_p
        l.hvd_msgtable_pending.argtypes = [ctypes.c_void_p]
        l.hvd_msgtable_reported_ranks.restype = ctypes.c_char_p
        l.hvd_msgtable_reported_ranks.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_char_p]

        l.hvd_fusion_plan.restype = ctypes.c_int
        l.hvd_fusion_plan.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.c_int, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int)]

        l.hvd_queue_create.restype = ctypes.c_void_p
        l.hvd_queue_destroy.argtypes = [ctypes.c_void_p]
        l.hvd_queue_add.restype = ctypes.c_int
        l.hvd_queue_add.argtypes = sig_args
        l.hvd_queue_finish.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_queue_size.restype = ctypes.c_int64
        l.hvd_queue_size.argtypes = [ctypes.c_void_p]
        l.hvd_queue_pop.restype = ctypes.c_char_p
        l.hvd_queue_pop.argtypes = [ctypes.c_void_p, ctypes.c_int64]

        l.hvd_stall_create.restype = ctypes.c_void_p
        l.hvd_stall_create.argtypes = [ctypes.c_double, ctypes.c_double,
                                       ctypes.c_int]
        l.hvd_stall_destroy.argtypes = [ctypes.c_void_p]
        l.hvd_stall_record.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_double]
        l.hvd_stall_done.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_stall_check.restype = ctypes.c_int
        l.hvd_stall_check.argtypes = [ctypes.c_void_p, ctypes.c_double,
                                      ctypes.POINTER(ctypes.c_char_p)]

        l.hvd_kv_start.restype = ctypes.c_void_p
        l.hvd_kv_start.argtypes = [ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_int)]
        l.hvd_kv_stop.argtypes = [ctypes.c_void_p]
        l.hvd_kv_destroy.argtypes = [ctypes.c_void_p]
        l.hvd_kv_port.restype = ctypes.c_int
        l.hvd_kv_port.argtypes = [ctypes.c_void_p]
        l.hvd_kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_int64]
        l.hvd_kv_get.restype = ctypes.POINTER(ctypes.c_uint8)
        l.hvd_kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int64)]
        l.hvd_kv_scan_json.restype = ctypes.c_void_p
        l.hvd_kv_scan_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        l.hvd_kv_free.argtypes = [ctypes.c_void_p]
        _lib = l
        return _lib


def _sig_args(name: str, dtype: str, shape: Sequence[int], op: int,
              prescale: float, postscale: float, ps_id: int):
    arr = (ctypes.c_int64 * len(shape))(*shape)
    return (name.encode(), dtype.encode(), arr, len(shape), op,
            prescale, postscale, ps_id)


CACHE_MISS, CACHE_HIT, CACHE_INVALID = 0, 1, 2


class NativeResponseCache:
    """LRU response cache (response_cache.h:45) backed by C++."""

    def __init__(self, capacity: int):
        self._l = lib()
        self._h = self._l.hvd_cache_create(capacity)

    def lookup(self, name, dtype, shape, op=0, prescale=1.0, postscale=1.0,
               ps_id=0) -> int:
        return self._l.hvd_cache_lookup(
            self._h, *_sig_args(name, dtype, shape, op, prescale, postscale,
                                ps_id))

    def put(self, name, dtype, shape, op=0, prescale=1.0, postscale=1.0,
            ps_id=0) -> int:
        return self._l.hvd_cache_put(
            self._h, *_sig_args(name, dtype, shape, op, prescale, postscale,
                                ps_id))

    def invalidate(self, name: str) -> bool:
        return bool(self._l.hvd_cache_invalidate(self._h, name.encode()))

    def clear(self):
        self._l.hvd_cache_clear(self._h)

    def __len__(self):
        return self._l.hvd_cache_size(self._h)

    def __del__(self):
        try:
            self._l.hvd_cache_destroy(self._h)
        except Exception:
            pass


class NativeMessageTable:
    """Coordinator negotiation table (controller.cc:1115)."""

    def __init__(self, world_size: int):
        self._l = lib()
        self._h = self._l.hvd_msgtable_create(world_size)

    def set_size(self, size: int):
        self._l.hvd_msgtable_set_size(self._h, size)

    def increment(self, name, dtype, shape, op, rank, prescale=1.0,
                  postscale=1.0, ps_id=0) -> int:
        """0 = recorded, 1 = ready, -1 = duplicate from this rank."""
        return self._l.hvd_msgtable_increment(
            self._h, *_sig_args(name, dtype, shape, op, prescale, postscale,
                                ps_id), rank)

    def validate(self, name: str) -> str:
        """'' when consistent across ranks; else the error text
        (ConstructResponse error checking)."""
        return self._l.hvd_msgtable_validate(self._h,
                                             name.encode()).decode()

    def erase(self, name: str):
        self._l.hvd_msgtable_erase(self._h, name.encode())

    def pending(self) -> List[str]:
        raw = self._l.hvd_msgtable_pending(self._h).decode()
        return raw.split("\n") if raw else []

    def reported_ranks(self, name: str) -> List[int]:
        raw = self._l.hvd_msgtable_reported_ranks(
            self._h, name.encode()).decode()
        return [int(r) for r in raw.split(",")] if raw else []

    def __del__(self):
        try:
            self._l.hvd_msgtable_destroy(self._h)
        except Exception:
            pass


def plan_fusion(entries: Sequence[Tuple[str, str, int, int, int]],
                threshold_bytes: int) -> List[List[int]]:
    """Fusion buckets (controller.cc:901 FuseResponses).

    entries: (name, dtype, bytes, op, process_set_id) per tensor, in
    submission order.  Returns lists of entry indices per bucket."""
    l = lib()
    n = len(entries)
    if n == 0:
        return []
    names = (ctypes.c_char_p * n)(*[e[0].encode() for e in entries])
    dtypes = (ctypes.c_char_p * n)(*[e[1].encode() for e in entries])
    nbytes = (ctypes.c_int64 * n)(*[e[2] for e in entries])
    ops = (ctypes.c_int * n)(*[e[3] for e in entries])
    ps = (ctypes.c_int * n)(*[e[4] for e in entries])
    out = (ctypes.c_int * n)()
    nb = l.hvd_fusion_plan(names, dtypes, nbytes, ops, ps, n,
                           threshold_bytes, out)
    buckets: List[List[int]] = [[] for _ in range(nb)]
    for i in range(n):
        buckets[out[i]].append(i)
    return buckets


class NativeTensorQueue:
    """Thread-safe pending-op queue (tensor_queue.h:28)."""

    def __init__(self):
        self._l = lib()
        self._h = self._l.hvd_queue_create()

    def add(self, name, dtype, shape, op=0, prescale=1.0, postscale=1.0,
            ps_id=0) -> bool:
        """False on duplicate in-flight name (DUPLICATE_NAME_ERROR)."""
        return bool(self._l.hvd_queue_add(
            self._h, *_sig_args(name, dtype, shape, op, prescale, postscale,
                                ps_id)))

    def finish(self, name: str):
        self._l.hvd_queue_finish(self._h, name.encode())

    def pop(self, max_items: int = 64) -> List[str]:
        raw = self._l.hvd_queue_pop(self._h, max_items).decode()
        return raw.split("\n") if raw else []

    def __len__(self):
        return self._l.hvd_queue_size(self._h)

    def __del__(self):
        try:
            self._l.hvd_queue_destroy(self._h)
        except Exception:
            pass


class NativeStallInspector:
    """Stalled-collective detector (stall_inspector.h:30)."""

    OK, WARN, SHUTDOWN = 0, 1, 2

    def __init__(self, warning_time_s: float = 60.0,
                 shutdown_time_s: float = 0.0, world_size: int = 1):
        self._l = lib()
        self._h = self._l.hvd_stall_create(warning_time_s, shutdown_time_s,
                                           world_size)

    def record_request(self, name: str, rank: int, now: float):
        self._l.hvd_stall_record(self._h, name.encode(), rank, now)

    def record_done(self, name: str):
        self._l.hvd_stall_done(self._h, name.encode())

    def check(self, now: float):
        """Returns (status, [(name, waited_s, ready_ranks, missing_ranks)])."""
        report = ctypes.c_char_p()
        status = self._l.hvd_stall_check(self._h, now, ctypes.byref(report))
        out = []
        raw = (report.value or b"").decode()
        for line in raw.splitlines():
            name, waited, ready, missing = line.split(";")
            out.append((name, float(waited),
                        [int(r) for r in ready.split(",") if r],
                        [int(r) for r in missing.split(",") if r]))
        return status, out

    def __del__(self):
        try:
            self._l.hvd_stall_destroy(self._h)
        except Exception:
            pass


class NativeKVServer:
    """C++ HTTP KV/rendezvous server (csrc/kv_server.cc) — same wire
    protocol as the Python ``_KVHandler``; per-request host CPU is ~10x
    cheaper, which is the control-plane latency floor at np >= 16 on a
    one-core launcher host.  The store stays readable (get/scan) after
    ``stop()`` until the object dies — launcher code gathers results after
    shutdown (runner/__init__.py)."""

    def __init__(self):
        self._l = lib()
        self._h = None
        self.port = None

    def start(self, port: int = 0) -> int:
        actual = ctypes.c_int(0)
        h = self._l.hvd_kv_start(port, ctypes.byref(actual))
        if not h:
            raise OSError(f"native KV server failed to bind port {port}")
        self._h = h
        self.port = actual.value
        return self.port

    def stop(self) -> None:
        if self._h is not None:
            self._l.hvd_kv_stop(self._h)

    def put(self, scope: str, key: str, value: bytes) -> None:
        self._l.hvd_kv_put(self._h, scope.encode(), key.encode(), value,
                           len(value))

    def get(self, scope: str, key: str) -> Optional[bytes]:
        n = ctypes.c_int64(-1)
        p = self._l.hvd_kv_get(self._h, scope.encode(), key.encode(),
                               ctypes.byref(n))
        if not p:
            return None
        try:
            return ctypes.string_at(p, n.value)
        finally:
            self._l.hvd_kv_free(p)

    def scan_scope(self, scope: str) -> dict:
        import base64
        import json
        p = self._l.hvd_kv_scan_json(self._h, scope.encode())
        if not p:
            return {}
        try:
            raw = ctypes.string_at(p)
        finally:
            self._l.hvd_kv_free(p)
        return {k: base64.b64decode(v)
                for k, v in json.loads(raw.decode()).items()}

    def __del__(self):
        try:
            if self._h is not None:
                self._l.hvd_kv_destroy(self._h)
        except Exception:
            pass
