// hvd_core.cc — native control-plane core for the TPU-native framework.
//
// Reference (horovod/common/, SURVEY.md §2.1): this file reimplements the
// pieces of Horovod's C++ core that remain host-side on TPU — the
// coordinator/worker negotiation logic (controller.cc:74 ComputeResponseList,
// :496 ConstructResponse, :1115 IncrementTensorCount), the ResponseCache
// (response_cache.h:45 — LRU keyed by tensor name+params, 3-bit status,
// INVALID on shape change), the fusion planner (controller.cc:901
// FuseResponses — ≤threshold buckets with mixed-dtype look-ahead), the
// TensorQueue (tensor_queue.h:28), and the StallInspector
// (stall_inspector.h:30 — warn when a strict subset of ranks reported a
// tensor for >warning_time, optional shutdown).
//
// What does NOT live here, by design: collective execution.  On TPU the data
// plane is XLA collectives inside compiled programs; this core only decides
// *whether/what/how* to dispatch (negotiation, caching, fusion, stall
// tracking).  Transport between ranks is handled by the Python layer (HTTP
// KV rendezvous — the Gloo-store analog); the logic here is transport-free,
// which also makes it unit-testable single-process.
//
// Exposed as a plain C ABI (see extern "C" block) consumed via ctypes
// (horovod_tpu/csrc/__init__.py), mirroring how the reference exposes
// operations.cc's extern "C" API through common/basics.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace hvd {

// ---------------------------------------------------------------------------
// Common types
// ---------------------------------------------------------------------------

struct TensorSig {
  std::string name;
  std::string dtype;
  std::vector<int64_t> shape;
  int op;              // ReduceOp / collective kind id
  double prescale;
  double postscale;
  int process_set_id;

  bool ParamsMatch(const TensorSig& o) const {
    return dtype == o.dtype && shape == o.shape && op == o.op &&
           prescale == o.prescale && postscale == o.postscale &&
           process_set_id == o.process_set_id;
  }
};

// ---------------------------------------------------------------------------
// ResponseCache (response_cache.h:45-90)
// ---------------------------------------------------------------------------

// 3-bit status mirror of the reference's CacheState.
enum CacheResult { CACHE_MISS = 0, CACHE_HIT = 1, CACHE_INVALID = 2 };

class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}

  int Lookup(const TensorSig& sig) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(sig.name);
    if (it == index_.end()) return CACHE_MISS;
    const TensorSig& cached = it->second->sig;
    if (!cached.ParamsMatch(sig)) {
      // Shape/param change invalidates (response_cache INVALID → forces
      // renegotiation; reference controller.cc:92-128 classification).
      return CACHE_INVALID;
    }
    // LRU touch.
    lru_.splice(lru_.begin(), lru_, it->second);
    return CACHE_HIT;
  }

  // Put after successful negotiation; assigns a stable cache bit.  Returns
  // the assigned bit (the reference synchronizes bit vectors across ranks —
  // bits are assigned in identical order because negotiation completes in
  // identical order on all ranks).
  int64_t Put(const TensorSig& sig) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(sig.name);
    if (it != index_.end()) {
      it->second->sig = sig;
      lru_.splice(lru_.begin(), lru_, it->second);
      return it->second->bit;
    }
    if (capacity_ == 0) return -1;
    if (lru_.size() >= capacity_) {
      // Evict LRU tail.
      auto& victim = lru_.back();
      free_bits_.insert(victim.bit);
      index_.erase(victim.sig.name);
      lru_.pop_back();
    }
    int64_t bit;
    if (!free_bits_.empty()) {
      bit = *free_bits_.begin();
      free_bits_.erase(free_bits_.begin());
    } else {
      bit = next_bit_++;
    }
    lru_.push_front(Entry{sig, bit});
    index_[sig.name] = lru_.begin();
    return bit;
  }

  bool Invalidate(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = index_.find(name);
    if (it == index_.end()) return false;
    free_bits_.insert(it->second->bit);
    lru_.erase(it->second);
    index_.erase(it);
    return true;
  }

  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    lru_.clear();
    index_.clear();
    free_bits_.clear();
    next_bit_ = 0;
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return lru_.size();
  }

 private:
  struct Entry {
    TensorSig sig;
    int64_t bit;
  };
  size_t capacity_;
  std::mutex mu_;
  std::list<Entry> lru_;
  std::unordered_map<std::string, std::list<Entry>::iterator> index_;
  std::set<int64_t> free_bits_;
  int64_t next_bit_ = 0;
};

// ---------------------------------------------------------------------------
// MessageTable / negotiation (controller.cc:1115 IncrementTensorCount,
// :496 ConstructResponse)
// ---------------------------------------------------------------------------

class MessageTable {
 public:
  explicit MessageTable(int size) : size_(size) {}

  void SetSize(int size) {
    std::lock_guard<std::mutex> lk(mu_);
    size_ = size;
  }

  // Record rank's request for a named collective.  Returns:
  //   0  -> recorded, not yet ready
  //   1  -> ready (every rank reported)
  //  -1  -> duplicate submission from this rank (DUPLICATE_NAME_ERROR,
  //         common.h:239)
  int Increment(const TensorSig& sig, int rank) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& rec = table_[sig.name];
    if (rec.ranks.count(rank)) return -1;
    rec.ranks.insert(rank);
    rec.sigs.push_back({rank, sig});
    if (rec.first_ts == 0) rec.first_ts = ++clock_;
    return (int)rec.ranks.size() == size_ ? 1 : 0;
  }

  // Validate cross-rank consistency once ready (ConstructResponse error
  // checking: mismatched dtypes / shapes / ops produce an ERROR response).
  // Returns empty string when consistent, else the error text.
  std::string Validate(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = table_.find(name);
    if (it == table_.end()) return "unknown tensor " + name;
    auto& sigs = it->second.sigs;
    if (sigs.empty()) return "no requests for " + name;
    const TensorSig& ref = sigs.front().second;
    for (auto& [rank, sig] : sigs) {
      if (sig.dtype != ref.dtype) {
        return "Mismatched data types for collective " + name + ": rank " +
               std::to_string(sigs.front().first) + " sent " + ref.dtype +
               ", rank " + std::to_string(rank) + " sent " + sig.dtype;
      }
      if (sig.op != ref.op) {
        return "Mismatched ops for collective " + name;
      }
      if (sig.process_set_id != ref.process_set_id) {
        return "Mismatched process sets for collective " + name + ": rank " +
               std::to_string(sigs.front().first) + " used set " +
               std::to_string(ref.process_set_id) + ", rank " +
               std::to_string(rank) + " used set " +
               std::to_string(sig.process_set_id);
      }
      if (sig.prescale != ref.prescale || sig.postscale != ref.postscale) {
        return "Mismatched prescale/postscale factors for collective " + name;
      }
      // Allreduce-family requires identical shapes; allgather-family
      // (op in [1000, 2000) by convention, see negotiation.py KIND_IDS)
      // permits differing dim0.
      bool allgather_like = sig.op >= 1000 && sig.op < 2000;
      if (allgather_like) {
        if (sig.shape.size() != ref.shape.size())
          return "Mismatched ranks (ndims) for allgather " + name;
        for (size_t i = 1; i < sig.shape.size(); ++i)
          if (sig.shape[i] != ref.shape[i])
            return "Mismatched trailing dimensions for allgather " + name;
      } else if (sig.shape != ref.shape) {
        return "Mismatched shapes for collective " + name;
      }
    }
    return "";
  }

  // Remove the record (after response delivered).
  void Erase(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    table_.erase(name);
  }

  // Ranks that have reported `name` so far.
  std::vector<int> ReportedRanks(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<int> out;
    auto it = table_.find(name);
    if (it != table_.end())
      out.assign(it->second.ranks.begin(), it->second.ranks.end());
    return out;
  }

  // Pending tensors in arrival order (for stall inspection / fusion scan).
  std::vector<std::string> Pending() {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::pair<uint64_t, std::string>> items;
    for (auto& [name, rec] : table_)
      items.push_back({rec.first_ts, name});
    std::sort(items.begin(), items.end());
    std::vector<std::string> out;
    for (auto& [ts, name] : items) out.push_back(name);
    return out;
  }

 private:
  struct Record {
    std::set<int> ranks;
    std::vector<std::pair<int, TensorSig>> sigs;
    uint64_t first_ts = 0;
  };
  int size_;
  std::mutex mu_;
  uint64_t clock_ = 0;
  std::unordered_map<std::string, Record> table_;
};

// ---------------------------------------------------------------------------
// Fusion planner (controller.cc:901 FuseResponses)
// ---------------------------------------------------------------------------

// Given an ordered list of ready entries, produce fusion buckets: greedy fill
// up to threshold bytes, only fusing entries with identical
// (dtype, op, process_set); the look-ahead continues scanning past a
// non-matching entry to fill the current bucket (reference look-ahead for
// mixed dtypes), preserving relative order within buckets.
struct FusionEntry {
  TensorSig sig;
  int64_t bytes;
};

static std::vector<std::vector<int>> PlanFusion(
    const std::vector<FusionEntry>& entries, int64_t threshold) {
  std::vector<std::vector<int>> buckets;
  std::vector<bool> used(entries.size(), false);
  for (size_t i = 0; i < entries.size(); ++i) {
    if (used[i]) continue;
    std::vector<int> bucket{(int)i};
    used[i] = true;
    int64_t total = entries[i].bytes;
    const TensorSig& key = entries[i].sig;
    for (size_t j = i + 1; j < entries.size(); ++j) {
      if (used[j]) continue;
      const auto& e = entries[j];
      if (e.sig.dtype != key.dtype || e.sig.op != key.op ||
          e.sig.process_set_id != key.process_set_id)
        continue;  // look-ahead: skip, keep scanning
      if (total + e.bytes > threshold) continue;
      bucket.push_back((int)j);
      used[j] = true;
      total += e.bytes;
    }
    buckets.push_back(std::move(bucket));
  }
  return buckets;
}

// ---------------------------------------------------------------------------
// TensorQueue (tensor_queue.h:28)
// ---------------------------------------------------------------------------

class TensorQueue {
 public:
  // Returns false on duplicate in-flight name (DUPLICATE_NAME_ERROR).
  bool Add(const TensorSig& sig) {
    std::lock_guard<std::mutex> lk(mu_);
    if (inflight_.count(sig.name)) return false;
    inflight_.insert(sig.name);
    queue_.push_back(sig);
    return true;
  }

  // Pop up to max entries (one negotiation cycle's worth,
  // PopMessagesFromQueue).
  std::vector<TensorSig> Pop(size_t max) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<TensorSig> out;
    while (!queue_.empty() && out.size() < max) {
      out.push_back(queue_.front());
      queue_.pop_front();
    }
    return out;
  }

  void Finish(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    inflight_.erase(name);
    // Drop any unpopped queue entry too — callers that use the queue purely
    // for duplicate detection (claim/finish) must not leak deque entries.
    for (auto it = queue_.begin(); it != queue_.end(); ++it) {
      if (it->name == name) {
        queue_.erase(it);
        break;
      }
    }
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return queue_.size();
  }

 private:
  std::mutex mu_;
  std::deque<TensorSig> queue_;
  std::set<std::string> inflight_;
};

// ---------------------------------------------------------------------------
// StallInspector (stall_inspector.h:30)
// ---------------------------------------------------------------------------

class StallInspector {
 public:
  StallInspector(double warn_s, double shutdown_s, int world_size)
      : warn_s_(warn_s), shutdown_s_(shutdown_s), size_(world_size) {}

  void RecordRequest(const std::string& name, int rank, double now) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& rec = pending_[name];
    if (rec.ranks.empty()) rec.first_seen = now;
    rec.ranks.insert(rank);
  }

  void RecordDone(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    pending_.erase(name);
  }

  // Build a warning report: tensors whose request set is a strict subset of
  // ranks for longer than warn_s.  Format (one line per tensor):
  //   name;waiting_secs;ready_ranks_csv;missing_ranks_csv
  // Returns 2 if any tensor exceeded shutdown_s (caller should abort,
  // HOROVOD_STALL_SHUTDOWN_TIME_SECONDS), 1 if warnings exist, else 0.
  int Check(double now, std::string* report) {
    std::lock_guard<std::mutex> lk(mu_);
    int status = 0;
    report->clear();
    for (auto& [name, rec] : pending_) {
      double waited = now - rec.first_seen;
      if ((int)rec.ranks.size() < size_ && waited > warn_s_) {
        status = std::max(status, 1);
        if (shutdown_s_ > 0 && waited > shutdown_s_) status = 2;
        std::string ready, missing;
        for (int r = 0; r < size_; ++r) {
          if (rec.ranks.count(r)) {
            if (!ready.empty()) ready += ",";
            ready += std::to_string(r);
          } else {
            if (!missing.empty()) missing += ",";
            missing += std::to_string(r);
          }
        }
        *report += name + ";" + std::to_string(waited) + ";" + ready + ";" +
                   missing + "\n";
      }
    }
    return status;
  }

 private:
  struct Rec {
    std::set<int> ranks;
    double first_seen = 0;
  };
  double warn_s_, shutdown_s_;
  int size_;
  std::mutex mu_;
  std::unordered_map<std::string, Rec> pending_;
};

}  // namespace hvd

// ---------------------------------------------------------------------------
// C ABI (ctypes surface — the operations.cc extern "C" analog)
// ---------------------------------------------------------------------------

using hvd::CacheResult;
using hvd::FusionEntry;
using hvd::MessageTable;
using hvd::ResponseCache;
using hvd::StallInspector;
using hvd::TensorQueue;
using hvd::TensorSig;

static TensorSig MakeSig(const char* name, const char* dtype,
                         const int64_t* shape, int ndim, int op,
                         double prescale, double postscale, int ps_id) {
  TensorSig s;
  s.name = name;
  s.dtype = dtype;
  s.shape.assign(shape, shape + ndim);
  s.op = op;
  s.prescale = prescale;
  s.postscale = postscale;
  s.process_set_id = ps_id;
  return s;
}

// Thread-local error/report buffer for string returns.
static thread_local std::string g_strbuf;

extern "C" {

// --- version ---------------------------------------------------------------
int hvd_core_abi_version() { return 2; }

// --- ResponseCache ----------------------------------------------------------
void* hvd_cache_create(int64_t capacity) {
  return new ResponseCache((size_t)capacity);
}
void hvd_cache_destroy(void* c) { delete (ResponseCache*)c; }
int hvd_cache_lookup(void* c, const char* name, const char* dtype,
                     const int64_t* shape, int ndim, int op, double prescale,
                     double postscale, int ps_id) {
  return ((ResponseCache*)c)
      ->Lookup(MakeSig(name, dtype, shape, ndim, op, prescale, postscale,
                       ps_id));
}
int64_t hvd_cache_put(void* c, const char* name, const char* dtype,
                      const int64_t* shape, int ndim, int op, double prescale,
                      double postscale, int ps_id) {
  return ((ResponseCache*)c)
      ->Put(MakeSig(name, dtype, shape, ndim, op, prescale, postscale,
                    ps_id));
}
int hvd_cache_invalidate(void* c, const char* name) {
  return ((ResponseCache*)c)->Invalidate(name) ? 1 : 0;
}
void hvd_cache_clear(void* c) { ((ResponseCache*)c)->Clear(); }
int64_t hvd_cache_size(void* c) { return (int64_t)((ResponseCache*)c)->Size(); }

// --- MessageTable ------------------------------------------------------------
void* hvd_msgtable_create(int world_size) {
  return new MessageTable(world_size);
}
void hvd_msgtable_destroy(void* t) { delete (MessageTable*)t; }
void hvd_msgtable_set_size(void* t, int size) {
  ((MessageTable*)t)->SetSize(size);
}
int hvd_msgtable_increment(void* t, const char* name, const char* dtype,
                           const int64_t* shape, int ndim, int op,
                           double prescale, double postscale, int ps_id,
                           int rank) {
  return ((MessageTable*)t)
      ->Increment(MakeSig(name, dtype, shape, ndim, op, prescale, postscale,
                          ps_id),
                  rank);
}
const char* hvd_msgtable_validate(void* t, const char* name) {
  g_strbuf = ((MessageTable*)t)->Validate(name);
  return g_strbuf.c_str();
}
void hvd_msgtable_erase(void* t, const char* name) {
  ((MessageTable*)t)->Erase(name);
}
const char* hvd_msgtable_pending(void* t) {
  auto pending = ((MessageTable*)t)->Pending();
  g_strbuf.clear();
  for (auto& p : pending) {
    if (!g_strbuf.empty()) g_strbuf += "\n";
    g_strbuf += p;
  }
  return g_strbuf.c_str();
}
const char* hvd_msgtable_reported_ranks(void* t, const char* name) {
  auto ranks = ((MessageTable*)t)->ReportedRanks(name);
  g_strbuf.clear();
  for (auto r : ranks) {
    if (!g_strbuf.empty()) g_strbuf += ",";
    g_strbuf += std::to_string(r);
  }
  return g_strbuf.c_str();
}

// --- Fusion planner -----------------------------------------------------------
// entries flattened: for i in [0, n): names[i], dtypes[i], bytes[i], ops[i],
// ps_ids[i].  Output: bucket index per entry written to out_bucket (len n).
// Returns the number of buckets.
int hvd_fusion_plan(const char** names, const char** dtypes,
                    const int64_t* bytes, const int* ops, const int* ps_ids,
                    int n, int64_t threshold, int* out_bucket) {
  std::vector<FusionEntry> entries(n);
  for (int i = 0; i < n; ++i) {
    entries[i].sig.name = names[i];
    entries[i].sig.dtype = dtypes[i];
    entries[i].sig.op = ops[i];
    entries[i].sig.process_set_id = ps_ids[i];
    entries[i].sig.prescale = 1.0;
    entries[i].sig.postscale = 1.0;
    entries[i].bytes = bytes[i];
  }
  auto buckets = hvd::PlanFusion(entries, threshold);
  for (size_t b = 0; b < buckets.size(); ++b)
    for (int idx : buckets[b]) out_bucket[idx] = (int)b;
  return (int)buckets.size();
}

// --- TensorQueue ----------------------------------------------------------------
void* hvd_queue_create() { return new TensorQueue(); }
void hvd_queue_destroy(void* q) { delete (TensorQueue*)q; }
int hvd_queue_add(void* q, const char* name, const char* dtype,
                  const int64_t* shape, int ndim, int op, double prescale,
                  double postscale, int ps_id) {
  return ((TensorQueue*)q)
                 ->Add(MakeSig(name, dtype, shape, ndim, op, prescale,
                               postscale, ps_id))
             ? 1
             : 0;
}
void hvd_queue_finish(void* q, const char* name) {
  ((TensorQueue*)q)->Finish(name);
}
int64_t hvd_queue_size(void* q) { return (int64_t)((TensorQueue*)q)->Size(); }
// Pop up to max names (newline-joined).
const char* hvd_queue_pop(void* q, int64_t max) {
  auto sigs = ((TensorQueue*)q)->Pop((size_t)max);
  g_strbuf.clear();
  for (auto& s : sigs) {
    if (!g_strbuf.empty()) g_strbuf += "\n";
    g_strbuf += s.name;
  }
  return g_strbuf.c_str();
}

// --- StallInspector ----------------------------------------------------------------
void* hvd_stall_create(double warn_s, double shutdown_s, int world_size) {
  return new StallInspector(warn_s, shutdown_s, world_size);
}
void hvd_stall_destroy(void* s) { delete (StallInspector*)s; }
void hvd_stall_record(void* s, const char* name, int rank, double now) {
  ((StallInspector*)s)->RecordRequest(name, rank, now);
}
void hvd_stall_done(void* s, const char* name) {
  ((StallInspector*)s)->RecordDone(name);
}
int hvd_stall_check(void* s, double now, const char** report) {
  int status = ((StallInspector*)s)->Check(now, &g_strbuf);
  *report = g_strbuf.c_str();
  return status;
}

}  // extern "C"
