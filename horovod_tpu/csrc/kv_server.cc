// kv_server.cc — native HTTP KV/rendezvous server for the TPU control plane.
//
// Reference (SURVEY.md §2.5): horovod/runner/http/http_server.py:35
// (KVStoreHandler) is the reference's rendezvous/KV transport; its C++ core
// keeps the controller's per-cycle exchange off the Python interpreter via
// MPI_Gatherv (mpi_controller.cc:135).  This file plays both roles for the
// TPU build: the SAME wire protocol as horovod_tpu/runner/http_server.py's
// Python server (PUT/GET/POST/DELETE, long-poll ?wait=, put-then-await
// POST ?ascope/akey, min-keys scans, batch puts) served from C++, so every
// control-plane request — negotiation announces, verdict waits, dispatch
// stream flushes, elastic rendezvous — costs microseconds of host CPU
// instead of a pure-Python http.server pass.  On the launcher's single host
// core the per-request CPU cost IS the control-plane latency floor at
// np >= 16 (measured: ~180 us/request Python, ~15 us native), which is what
// makes new-signature negotiation growth sublinear in np.
//
// The Python server stays as the fallback (HVD_TPU_KV_SERVER=python or a
// failed native build); behavior parity is pinned by running the KV endpoint
// unit tests against BOTH implementations (tests/test_runner.py).
//
// Concurrency model mirrors the Python one deliberately: one global store
// mutex, per-scope condition variables (a PUT wakes only its scope's
// waiters), waiters re-fetch their scope's condition every loop iteration so
// a scope delete can retire a condition object without stranding sleepers.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace hvdkv {

// ---------------------------------------------------------------------------
// Small codecs (base64, percent, JSON string-map)
// ---------------------------------------------------------------------------

static const char kB64[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

static std::string b64encode(const std::string& in) {
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8 |
                 (uint8_t)in[i + 2];
    out += kB64[v >> 18];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += kB64[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16;
    out += kB64[v >> 18];
    out += kB64[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16 | (uint8_t)in[i + 1] << 8;
    out += kB64[v >> 18];
    out += kB64[(v >> 12) & 63];
    out += kB64[(v >> 6) & 63];
    out += '=';
  }
  return out;
}

static int b64val(char c) {
  if (c >= 'A' && c <= 'Z') return c - 'A';
  if (c >= 'a' && c <= 'z') return c - 'a' + 26;
  if (c >= '0' && c <= '9') return c - '0' + 52;
  if (c == '+') return 62;
  if (c == '/') return 63;
  return -1;
}

static bool b64decode(const std::string& in, std::string* out) {
  out->clear();
  uint32_t acc = 0;
  int nbits = 0;
  for (char c : in) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int v = b64val(c);
    if (v < 0) return false;
    acc = (acc << 6) | v;
    nbits += 6;
    if (nbits >= 8) {
      nbits -= 8;
      out->push_back((char)((acc >> nbits) & 0xff));
    }
  }
  return true;
}

static int hexval(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

static std::string pct_decode(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size()) {
      int h = hexval(in[i + 1]), l = hexval(in[i + 2]);
      if (h >= 0 && l >= 0) {
        out.push_back((char)(h * 16 + l));
        i += 2;
        continue;
      }
    }
    out.push_back(in[i]);
  }
  return out;
}

static void utf8_append(std::string* out, uint32_t cp) {
  if (cp < 0x80) {
    out->push_back((char)cp);
  } else if (cp < 0x800) {
    out->push_back((char)(0xC0 | (cp >> 6)));
    out->push_back((char)(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out->push_back((char)(0xE0 | (cp >> 12)));
    out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back((char)(0x80 | (cp & 0x3F)));
  } else {
    out->push_back((char)(0xF0 | (cp >> 18)));
    out->push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
    out->push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back((char)(0x80 | (cp & 0x3F)));
  }
}

// Parse one JSON string starting at in[*i] (which must be '"'); advance *i
// past the closing quote.  Handles the escapes json.dumps emits, including
// \uXXXX surrogate pairs (tensor names are user input).
static bool json_string(const std::string& in, size_t* i, std::string* out) {
  out->clear();
  if (*i >= in.size() || in[*i] != '"') return false;
  ++*i;
  while (*i < in.size()) {
    char c = in[*i];
    if (c == '"') {
      ++*i;
      return true;
    }
    if (c == '\\') {
      if (*i + 1 >= in.size()) return false;
      char e = in[*i + 1];
      *i += 2;
      switch (e) {
        case '"': out->push_back('"'); break;
        case '\\': out->push_back('\\'); break;
        case '/': out->push_back('/'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'n': out->push_back('\n'); break;
        case 'r': out->push_back('\r'); break;
        case 't': out->push_back('\t'); break;
        case 'u': {
          if (*i + 4 > in.size()) return false;
          uint32_t cp = 0;
          for (int k = 0; k < 4; ++k) {
            int v = hexval(in[*i + k]);
            if (v < 0) return false;
            cp = cp * 16 + v;
          }
          *i += 4;
          if (cp >= 0xD800 && cp <= 0xDBFF && *i + 6 <= in.size() &&
              in[*i] == '\\' && in[*i + 1] == 'u') {
            uint32_t lo = 0;
            bool ok = true;
            for (int k = 0; k < 4; ++k) {
              int v = hexval(in[*i + 2 + k]);
              if (v < 0) { ok = false; break; }
              lo = lo * 16 + v;
            }
            if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              *i += 6;
            }
          }
          utf8_append(out, cp);
          break;
        }
        default: return false;
      }
      continue;
    }
    out->push_back(c);
    ++*i;
  }
  return false;
}

static void skip_ws(const std::string& in, size_t* i) {
  while (*i < in.size() && (in[*i] == ' ' || in[*i] == '\t' ||
                            in[*i] == '\n' || in[*i] == '\r'))
    ++*i;
}

// Parse a flat JSON object of string values: {"k": "v", ...}.
static bool json_strmap(const std::string& in,
                        std::vector<std::pair<std::string, std::string>>* out) {
  out->clear();
  size_t i = 0;
  skip_ws(in, &i);
  if (i >= in.size() || in[i] != '{') return false;
  ++i;
  skip_ws(in, &i);
  if (i < in.size() && in[i] == '}') return true;
  while (true) {
    std::string k, v;
    skip_ws(in, &i);
    if (!json_string(in, &i, &k)) return false;
    skip_ws(in, &i);
    if (i >= in.size() || in[i] != ':') return false;
    ++i;
    skip_ws(in, &i);
    if (!json_string(in, &i, &v)) return false;
    out->emplace_back(std::move(k), std::move(v));
    skip_ws(in, &i);
    if (i >= in.size()) return false;
    if (in[i] == ',') {
      ++i;
      continue;
    }
    if (in[i] == '}') return true;
    return false;
  }
}

// Serialize a JSON string: UTF-8 bytes pass through raw (json.loads accepts
// them); only the structural escapes and control bytes are escaped.
static void json_escape(const std::string& in, std::string* out) {
  out->push_back('"');
  for (unsigned char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back((char)c);
        }
    }
  }
  out->push_back('"');
}

// ---------------------------------------------------------------------------
// Store: scoped KV + per-scope conditions (mirrors _KVHandler's model)
// ---------------------------------------------------------------------------

struct Server {
  std::mutex m;
  std::map<std::string, std::unordered_map<std::string, std::string>> data;
  // shared_ptr so a scope delete can retire a condition while waiters still
  // hold it; they wake, re-check, and re-fetch a fresh one next iteration.
  std::map<std::string, std::shared_ptr<std::condition_variable>> conds;
  std::atomic<bool> stopping{false};
  int listen_fd = -1;
  int port = 0;
  std::set<int> client_fds;  // guarded by m
  std::thread accept_thread;

  std::shared_ptr<std::condition_variable> cond(const std::string& scope) {
    auto it = conds.find(scope);
    if (it != conds.end()) return it->second;
    auto c = std::make_shared<std::condition_variable>();
    conds[scope] = c;
    return c;
  }

  void notify(const std::string& scope) {
    auto it = conds.find(scope);
    if (it != conds.end()) it->second->notify_all();
  }

  void gc_cond(const std::string& scope) {
    auto it = conds.find(scope);
    if (it != conds.end()) {
      it->second->notify_all();
      conds.erase(it);
    }
  }

  void wake_all() {
    std::lock_guard<std::mutex> g(m);
    for (auto& kv : conds) kv.second->notify_all();
  }
};

// ---------------------------------------------------------------------------
// HTTP plumbing
// ---------------------------------------------------------------------------

// Framing caps: a header block or declared body beyond these answers 400
// and drops the connection instead of buffering without bound (a garbage or
// hostile peer could otherwise OOM the one launcher host the control plane
// runs on).  Control-plane payloads are small; result gathers and batch
// puts stay far under the body cap.
static const size_t kMaxHeaderBytes = size_t(1) << 20;   // 1 MiB
static const size_t kMaxBodyBytes = size_t(1) << 30;     // 1 GiB

struct Conn {
  int fd;
  std::string buf;   // unconsumed bytes
  bool ok = true;
  bool oversize = false;  // framing cap exceeded: answer 400, then close

  explicit Conn(int f) : fd(f) {}

  // Read until the buffer contains `delim`; returns position or npos.
  // Stops (oversize) once more than `cap` bytes accumulate without the
  // delimiter appearing.
  size_t read_until(const std::string& delim, size_t cap) {
    while (true) {
      size_t pos = buf.find(delim);
      if (pos != std::string::npos) {
        if (pos > cap) {
          oversize = true;
          return std::string::npos;
        }
        return pos;
      }
      if (buf.size() > cap + delim.size()) {
        oversize = true;
        return std::string::npos;
      }
      char tmp[8192];
      ssize_t n = recv(fd, tmp, sizeof(tmp), 0);
      if (n <= 0) {
        ok = false;
        return std::string::npos;
      }
      buf.append(tmp, n);
    }
  }

  bool read_n(size_t n, std::string* out, size_t cap) {
    if (n > cap) {
      oversize = true;
      return false;
    }
    while (buf.size() < n) {
      char tmp[8192];
      ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
      if (r <= 0) {
        ok = false;
        return false;
      }
      buf.append(tmp, r);
    }
    out->assign(buf, 0, n);
    buf.erase(0, n);
    return true;
  }

  void write_all(const std::string& s) {
    size_t off = 0;
    while (off < s.size()) {
      ssize_t n = send(fd, s.data() + off, s.size() - off, MSG_NOSIGNAL);
      if (n <= 0) {
        ok = false;
        return;
      }
      off += n;
    }
  }
};

struct Request {
  std::string method;
  std::string scope;          // decoded first path segment
  std::string key;            // decoded remaining segments joined with '/'
  std::map<std::string, std::string> query;
  std::string body;
};

static bool parse_request(Conn* c, Request* rq) {
  size_t hdr_end = c->read_until("\r\n\r\n", kMaxHeaderBytes);
  if (hdr_end == std::string::npos) return false;
  std::string head = c->buf.substr(0, hdr_end);
  c->buf.erase(0, hdr_end + 4);
  size_t line_end = head.find("\r\n");
  std::string reqline =
      line_end == std::string::npos ? head : head.substr(0, line_end);
  size_t sp1 = reqline.find(' ');
  size_t sp2 = reqline.rfind(' ');
  if (sp1 == std::string::npos || sp2 == sp1) return false;
  rq->method = reqline.substr(0, sp1);
  std::string target = reqline.substr(sp1 + 1, sp2 - sp1 - 1);
  // Content-Length (case-insensitive scan of the header block).
  size_t clen = 0;
  size_t pos = line_end;
  while (pos != std::string::npos && pos < head.size()) {
    size_t next = head.find("\r\n", pos + 2);
    std::string line = head.substr(
        pos + 2, next == std::string::npos ? std::string::npos
                                           : next - pos - 2);
    if (line.size() > 15) {
      std::string lower;
      for (char ch : line.substr(0, 15)) lower += (char)tolower(ch);
      if (lower == "content-length:")
        clen = strtoull(line.c_str() + 15, nullptr, 10);
    }
    pos = next;
  }
  // Split query, decode path segments.
  std::string path = target, qs;
  size_t qpos = target.find('?');
  if (qpos != std::string::npos) {
    path = target.substr(0, qpos);
    qs = target.substr(qpos + 1);
  }
  size_t start = path.find_first_not_of('/');
  std::vector<std::string> segs;
  if (start != std::string::npos) {
    std::string trimmed = path.substr(start);
    while (!trimmed.empty() && trimmed.back() == '/') trimmed.pop_back();
    size_t p = 0;
    while (true) {
      size_t slash = trimmed.find('/', p);
      segs.push_back(pct_decode(trimmed.substr(
          p, slash == std::string::npos ? std::string::npos : slash - p)));
      if (slash == std::string::npos) break;
      p = slash + 1;
    }
  }
  rq->scope = segs.empty() ? "" : segs[0];
  rq->key.clear();
  for (size_t i = 1; i < segs.size(); ++i) {
    if (i > 1) rq->key += '/';
    rq->key += segs[i];
  }
  rq->query.clear();
  size_t p = 0;
  while (p < qs.size()) {
    size_t amp = qs.find('&', p);
    std::string pair = qs.substr(
        p, amp == std::string::npos ? std::string::npos : amp - p);
    size_t eq = pair.find('=');
    if (eq != std::string::npos)
      rq->query[pct_decode(pair.substr(0, eq))] =
          pct_decode(pair.substr(eq + 1));
    if (amp == std::string::npos) break;
    p = amp + 1;
  }
  if (clen > 0) {
    if (!c->read_n(clen, &rq->body, kMaxBodyBytes)) return false;
  } else {
    rq->body.clear();
  }
  return true;
}

static void respond(Conn* c, int code, const std::string& body) {
  const char* text = code == 200   ? "OK"
                     : code == 404 ? "Not Found"
                                   : "Bad Request";
  std::string head = "HTTP/1.1 " + std::to_string(code) + " " + text +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\n\r\n";
  head += body;
  c->write_all(head);
}

static double query_double(const Request& rq, const char* name, double cap) {
  auto it = rq.query.find(name);
  if (it == rq.query.end()) return 0.0;
  char* end = nullptr;
  double v = strtod(it->second.c_str(), &end);
  if (end == it->second.c_str()) return 0.0;
  return v < cap ? v : cap;
}

// ---------------------------------------------------------------------------
// Endpoint handlers (parity with _KVHandler, horovod_tpu/runner/http_server.py)
// ---------------------------------------------------------------------------

using Clock = std::chrono::steady_clock;

static void handle_put(Server* s, Conn* c, const Request& rq) {
  {
    std::lock_guard<std::mutex> g(s->m);
    s->data[rq.scope][rq.key] = rq.body;
    s->notify(rq.scope);
  }
  respond(c, 200, "");
}

static void handle_batch_put(Server* s, Conn* c, const Request& rq) {
  std::vector<std::pair<std::string, std::string>> items;
  if (!json_strmap(rq.body.empty() ? std::string("{}") : rq.body, &items)) {
    respond(c, 400, "");
    return;
  }
  std::vector<std::pair<std::string, std::string>> decoded;
  decoded.reserve(items.size());
  for (auto& kv : items) {
    std::string raw;
    if (!b64decode(kv.second, &raw)) {
      respond(c, 400, "");
      return;
    }
    decoded.emplace_back(std::move(kv.first), std::move(raw));
  }
  {
    std::lock_guard<std::mutex> g(s->m);
    auto& scope = s->data[rq.scope];
    for (auto& kv : decoded) scope[kv.first] = std::move(kv.second);
    s->notify(rq.scope);
  }
  respond(c, 200, "");
}

static void handle_put_wait(Server* s, Conn* c, const Request& rq) {
  auto as = rq.query.find("ascope");
  auto ak = rq.query.find("akey");
  if (as == rq.query.end() || ak == rq.query.end()) {
    respond(c, 400, "");
    return;
  }
  double wait_s = query_double(rq, "wait", 60.0);
  std::string out;
  bool found = false;
  {
    std::unique_lock<std::mutex> g(s->m);
    s->data[rq.scope][rq.key] = rq.body;
    s->notify(rq.scope);
    auto deadline = Clock::now() +
                    std::chrono::duration_cast<Clock::duration>(
                        std::chrono::duration<double>(wait_s));
    while (!s->stopping) {
      auto sit = s->data.find(as->second);
      if (sit != s->data.end()) {
        auto kit = sit->second.find(ak->second);
        if (kit != sit->second.end()) {
          out = kit->second;
          found = true;
          break;
        }
      }
      auto now = Clock::now();
      if (now >= deadline) break;
      s->cond(as->second)->wait_until(g, deadline);
    }
  }
  if (!found)
    respond(c, 404, "");
  else
    respond(c, 200, out);
}

static void handle_get(Server* s, Conn* c, const Request& rq) {
  double wait_s = query_double(rq, "wait", 60.0);
  std::string out;
  bool found = false;
  {
    std::unique_lock<std::mutex> g(s->m);
    auto deadline = Clock::now() +
                    std::chrono::duration_cast<Clock::duration>(
                        std::chrono::duration<double>(wait_s));
    while (true) {
      auto sit = s->data.find(rq.scope);
      if (sit != s->data.end()) {
        auto kit = sit->second.find(rq.key);
        if (kit != sit->second.end()) {
          out = kit->second;
          found = true;
          break;
        }
      }
      if (wait_s <= 0 || s->stopping) break;
      auto now = Clock::now();
      if (now >= deadline) break;
      s->cond(rq.scope)->wait_until(g, deadline);
    }
  }
  if (!found)
    respond(c, 404, "");
  else
    respond(c, 200, out);
}

static void handle_scan(Server* s, Conn* c, const Request& rq) {
  double wait_s = query_double(rq, "wait", 60.0);
  long min_keys = 0;
  auto it = rq.query.find("min");
  if (it != rq.query.end()) min_keys = strtol(it->second.c_str(), nullptr, 10);
  std::vector<std::pair<std::string, std::string>> snapshot;
  {
    std::unique_lock<std::mutex> g(s->m);
    auto deadline = Clock::now() +
                    std::chrono::duration_cast<Clock::duration>(
                        std::chrono::duration<double>(wait_s));
    while (true) {
      auto sit = s->data.find(rq.scope);
      size_t n = sit == s->data.end() ? 0 : sit->second.size();
      if (min_keys <= 0 || (long)n >= min_keys || wait_s <= 0 ||
          s->stopping) {
        if (sit != s->data.end())
          snapshot.assign(sit->second.begin(), sit->second.end());
        break;
      }
      auto now = Clock::now();
      if (now >= deadline) {
        if (sit != s->data.end())
          snapshot.assign(sit->second.begin(), sit->second.end());
        break;
      }
      s->cond(rq.scope)->wait_until(g, deadline);
    }
  }
  std::string body = "{";
  bool first = true;
  for (auto& kv : snapshot) {
    if (!first) body += ", ";
    first = false;
    json_escape(kv.first, &body);
    body += ": ";
    json_escape(b64encode(kv.second), &body);
  }
  body += "}";
  respond(c, 200, body);
}

static void handle_delete(Server* s, Conn* c, const Request& rq) {
  {
    std::lock_guard<std::mutex> g(s->m);
    if (rq.key.empty()) {
      s->data.erase(rq.scope);
      s->gc_cond(rq.scope);
    } else {
      auto sit = s->data.find(rq.scope);
      if (sit != s->data.end()) {
        sit->second.erase(rq.key);
        if (sit->second.empty()) {
          s->data.erase(sit);
          s->gc_cond(rq.scope);
        }
      }
    }
  }
  respond(c, 200, "");
}

static void serve_conn(std::shared_ptr<Server> s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Conn c(fd);
  Request rq;
  while (!s->stopping && c.ok) {
    if (!parse_request(&c, &rq)) {
      // An over-cap header/body gets an explicit 400 before the close;
      // the stream position is unrecoverable, so the connection ends
      // either way.  Plain EOF/reset just closes.
      if (c.oversize && c.ok) respond(&c, 400, "");
      break;
    }
    if (rq.method == "PUT") {
      handle_put(s.get(), &c, rq);
    } else if (rq.method == "POST") {
      if (!rq.key.empty())
        handle_put_wait(s.get(), &c, rq);
      else
        handle_batch_put(s.get(), &c, rq);
    } else if (rq.method == "GET") {
      if (rq.key.empty())
        handle_scan(s.get(), &c, rq);
      else
        handle_get(s.get(), &c, rq);
    } else if (rq.method == "DELETE") {
      handle_delete(s.get(), &c, rq);
    } else {
      respond(&c, 400, "");
    }
  }
  {
    std::lock_guard<std::mutex> g(s->m);
    s->client_fds.erase(fd);
  }
  close(fd);
}

static void accept_loop(std::shared_ptr<Server> s) {
  while (!s->stopping) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stopping) break;
      if (errno == EINTR) continue;
      // Persistent accept errors (EMFILE under fd pressure) must not
      // busy-spin the one launcher core the control plane depends on.
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
      continue;
    }
    {
      std::lock_guard<std::mutex> g(s->m);
      if (s->stopping) {
        close(fd);
        break;
      }
      s->client_fds.insert(fd);
    }
    std::thread(serve_conn, s, fd).detach();
  }
}

// ---------------------------------------------------------------------------
// Registry + C ABI
// ---------------------------------------------------------------------------

static std::mutex g_reg_mutex;
static std::map<int64_t, std::shared_ptr<Server>> g_registry;
static int64_t g_next_id = 1;

static std::shared_ptr<Server> lookup(void* h) {
  std::lock_guard<std::mutex> g(g_reg_mutex);
  auto it = g_registry.find((int64_t)(intptr_t)h);
  return it == g_registry.end() ? nullptr : it->second;
}

}  // namespace hvdkv

extern "C" {

// Start a server on `port` (0 = ephemeral).  Returns an opaque handle
// (nullptr on failure); *actual_port receives the bound port.
void* hvd_kv_start(int port, int* actual_port) {
  using namespace hvdkv;
  auto s = std::make_shared<Server>();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) return nullptr;
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) < 0 ||
      listen(s->listen_fd, 128) < 0) {
    close(s->listen_fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  if (actual_port) *actual_port = s->port;
  s->accept_thread = std::thread(accept_loop, s);
  std::lock_guard<std::mutex> g(g_reg_mutex);
  int64_t id = g_next_id++;
  g_registry[id] = s;
  return (void*)(intptr_t)id;
}

// Stop serving: close the listener, wake every long-poll waiter, shut down
// client sockets.  The STORE stays readable through the in-process API
// (hvd_kv_get/hvd_kv_scan_json) until hvd_kv_destroy — launcher code reads
// gathered results after shutdown (runner/__init__.py result gather).
void hvd_kv_stop(void* h) {
  using namespace hvdkv;
  auto s = lookup(h);
  if (!s) return;
  if (s->stopping.exchange(true)) return;  // idempotent: destroy() re-calls,
  // and a recycled fd number must never be shut down twice
  shutdown(s->listen_fd, SHUT_RDWR);
  close(s->listen_fd);
  s->listen_fd = -1;
  s->wake_all();
  {
    std::lock_guard<std::mutex> g(s->m);
    for (int fd : s->client_fds) shutdown(fd, SHUT_RDWR);
  }
  if (s->accept_thread.joinable()) s->accept_thread.join();
}

void hvd_kv_destroy(void* h) {
  using namespace hvdkv;
  hvd_kv_stop(h);
  std::lock_guard<std::mutex> g(g_reg_mutex);
  g_registry.erase((int64_t)(intptr_t)h);
}

int hvd_kv_port(void* h) {
  auto s = hvdkv::lookup(h);
  return s ? s->port : -1;
}

void hvd_kv_put(void* h, const char* scope, const char* key,
                const uint8_t* value, int64_t len) {
  auto s = hvdkv::lookup(h);
  if (!s) return;
  std::lock_guard<std::mutex> g(s->m);
  s->data[scope][key] = std::string((const char*)value, (size_t)len);
  s->notify(scope);
}

// Returns a malloc'd copy (caller frees with hvd_kv_free); nullptr if absent.
uint8_t* hvd_kv_get(void* h, const char* scope, const char* key,
                    int64_t* len) {
  auto s = hvdkv::lookup(h);
  *len = -1;
  if (!s) return nullptr;
  std::lock_guard<std::mutex> g(s->m);
  auto sit = s->data.find(scope);
  if (sit == s->data.end()) return nullptr;
  auto kit = sit->second.find(key);
  if (kit == sit->second.end()) return nullptr;
  // malloc(0) may return nullptr, which the caller reads as "absent":
  // always allocate at least one byte so an empty value round-trips as b"".
  uint8_t* out = (uint8_t*)malloc(kit->second.size() + 1);
  if (!out) return nullptr;  // allocation failure reads as "absent"
  // (*len stays -1), never a memcpy through nullptr
  memcpy(out, kit->second.data(), kit->second.size());
  *len = (int64_t)kit->second.size();
  return out;
}

// Whole-scope snapshot as the same JSON {key: base64(value)} body the HTTP
// scan returns (caller frees with hvd_kv_free).
char* hvd_kv_scan_json(void* h, const char* scope) {
  using namespace hvdkv;
  auto s = lookup(h);
  if (!s) return nullptr;
  std::string body = "{";
  {
    std::lock_guard<std::mutex> g(s->m);
    auto sit = s->data.find(scope);
    bool first = true;
    if (sit != s->data.end()) {
      for (auto& kv : sit->second) {
        if (!first) body += ", ";
        first = false;
        json_escape(kv.first, &body);
        body += ": ";
        json_escape(b64encode(kv.second), &body);
      }
    }
  }
  body += "}";
  char* out = (char*)malloc(body.size() + 1);
  if (!out) return nullptr;
  memcpy(out, body.c_str(), body.size() + 1);
  return out;
}

void hvd_kv_free(void* p) { free(p); }

}  // extern "C"
